"""bench_gate: fail on >10% regressions between two BENCH_rNN.json rounds.

The ROADMAP's still-unpaid bench-regression gate (ISSUE 11 satellite):
perf landed between TPU runs could silently rot because nothing compared
BENCH_rNN against rNN-1. This tool does exactly that:

    python -m tools.bench_gate BENCH_r06.json BENCH_r05.json
    python tools/bench_gate.py NEW.json OLD.json --threshold 0.10

Input: either a raw bench metrics dict (the JSON line bench.py prints) or
a BENCH_rNN.json wrapper whose `parsed` field holds it. Only keys PRESENT
IN BOTH rounds are compared — new rows gate from their next round, removed
rows are reported but don't fail (a renamed row should be caught in
review, not silently dropped from the gate).

Direction is inferred per key: throughput-like keys (tok/s, tps, speedup,
rate, pct, concurrency, accepted) must not DROP more than the threshold;
latency/size-like keys (_ms, _s suffixes, ttft, latency, stall, bytes,
recover) must not RISE more than the threshold. Higher-is-better wins when
both patterns match (`prefix_ttft_speedup` is a speedup).

Exit codes: 0 = no regression, 1 = regression(s), 2 = usage/parse error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

DEFAULT_THRESHOLD = 0.10

# Checked FIRST: a key matching any of these is higher-is-better even when
# a lower-is-better marker also appears in it.
HIGHER_MARKERS = (
    "tok_per", "tokens_per", "tok/s", "tps", "speedup", "throughput",
    "rate", "pct", "percent", "concurrency", "accepted", "roofline",
    "fraction", "hits",
    # Speculative decoding rows (ISSUE 12, BENCH_SPEC_PAGED): accept rates,
    # accepted-tokens/s and the spec-vs-plain-paged ratios all gate
    # higher-is-better once two rounds share them; *_draft_hist is a dict
    # (skipped) and *_draft_ckpt_bytes rides the "bytes" lower-is-better
    # marker.
    "accept", "vs_paged",
    # Million-token context ladder (ISSUE 14, BENCH_LONGCTX):
    # longctx_<len>_prefill_tok_per_s / _decode_tok_per_s and the N-users-
    # one-document longctx_users_agg_tok_per_s ride "tok_per";
    # longctx_users_prefix_hit_rate rides "rate"/"hit_rate";
    # longctx_<len>_ttft_ms rides the "ttft"/"_ms" lower-is-better markers.
    # longctx_users_doc_tokens is a workload descriptor, not a metric —
    # "doc_tokens" pins it higher-is-better so a bigger benchmark document
    # can never read as a regression.
    "hit_rate", "doc_tokens",
    # Tree-batched parallel sampling rows (ISSUE 18, BENCH_FORK,
    # docs/TREE_SAMPLING.md) ride existing markers:
    # fork_best_of_{1,8}_decode_tok_per_s -> "tok_per" (higher),
    # fork_best_of_{1,8}_p99_ttft_ms -> "ttft"/"_ms"/"p99" (lower),
    # fork_kv_bytes_ratio -> "bytes" (lower: CoW forking must keep the
    # best-of-8 page peak near best-of-1, a rise means sharing broke),
    # fork_vs_clone_ttft_speedup -> "speedup" (higher, outranks "ttft").
)
LOWER_MARKERS = (
    "_ms", "_s", "ms_", "latency", "ttft", "stall", "bytes", "recover",
    "err", "p50", "p95", "p99", "overhead",
)

# Non-metric bookkeeping keys in bench payloads.
SKIP_KEYS = {"metric", "unit", "vs_baseline", "value"}


def direction(key: str) -> str:
    """'higher' (a drop regresses) or 'lower' (a rise regresses)."""
    k = key.lower()
    if any(m in k for m in HIGHER_MARKERS):
        return "higher"
    if any(m in k for m in LOWER_MARKERS):
        return "lower"
    return "higher"


def load_metrics(path: str) -> dict[str, float]:
    """Numeric metrics from a bench JSON (raw dict or BENCH_rNN wrapper)."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    out: dict[str, float] = {}
    for k, v in data.items():
        if k in SKIP_KEYS or isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[k] = float(v)
    return out


def compare(new: dict[str, float], old: dict[str, float],
            threshold: float = DEFAULT_THRESHOLD) -> dict:
    """{'regressions': [...], 'improvements': [...], 'missing': [...],
    'added': [...]} over the shared numeric keys."""
    regressions, improvements = [], []
    for key in sorted(set(new) & set(old)):
        a, b = old[key], new[key]
        if a == 0.0:
            continue  # no baseline signal — a ratio would be meaningless
        change = (b - a) / abs(a)
        d = direction(key)
        bad = -change if d == "higher" else change
        entry = {
            "key": key, "old": a, "new": b, "direction": d,
            "change_pct": round(change * 100.0, 2),
        }
        if bad > threshold:
            regressions.append(entry)
        elif bad < -threshold:
            improvements.append(entry)
    return {
        "regressions": regressions,
        "improvements": improvements,
        "missing": sorted(set(old) - set(new)),
        "added": sorted(set(new) - set(old)),
    }


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_gate",
        description="fail on >threshold regressions between bench rounds",
    )
    ap.add_argument("new", help="current round JSON (BENCH_rNN.json)")
    ap.add_argument("old", help="previous round JSON (BENCH_rNN-1.json)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="allowed fractional drop on shared keys "
                         "(default 0.10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full comparison as JSON")
    args = ap.parse_args(argv)
    if args.threshold <= 0:
        print("bench_gate: --threshold must be > 0", file=sys.stderr)
        return 2
    try:
        new = load_metrics(args.new)
        old = load_metrics(args.old)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_gate: {e}", file=sys.stderr)
        return 2
    result = compare(new, old, threshold=args.threshold)
    if args.json:
        print(json.dumps(result, indent=1, sort_keys=True))
    else:
        for r in result["regressions"]:
            print(f"REGRESSION {r['key']}: {r['old']} -> {r['new']} "
                  f"({r['change_pct']:+.1f}%, {r['direction']}-is-better)")
        for r in result["improvements"]:
            print(f"improved   {r['key']}: {r['old']} -> {r['new']} "
                  f"({r['change_pct']:+.1f}%)")
        if result["missing"]:
            print("missing vs previous round (not gated): "
                  + ", ".join(result["missing"]))
        n_shared = len(set(new) & set(old))
        print(f"bench_gate: {len(result['regressions'])} regression(s) over "
              f"{n_shared} shared key(s), threshold "
              f"{args.threshold * 100:.0f}%")
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
