"""Chaos harness driver (ISSUE 19, docs/ROBUSTNESS.md).

Runs a 2-replica tiny-model mini-cluster under phase-scheduled fault
scripts (localai_tpu.testing.faults.ChaosScript) and asserts the
robustness invariants the membership/failover layer promises:

  * zero hung callers — every drain thread joins inside its deadline;
  * every submitted request reaches exactly one terminal event;
  * a drained replica admits no new work, finishes its in-flight streams,
    and hands its span affinity to a survivor (snapshot reads 0 held);
  * grammar-constrained greedy output survives a mid-stream replica death
    byte-identical to the no-fault run (stateful replay, not abort);
  * the per-replica circuit breaker sends at most ONE probe per half-open
    window (asserted from journal events);
  * every journaled resource protocol balances (ISSUE 20): for each
    protocol declared with a `journal=` pair in tools/lint/resources.py
    (the same registry the resource-leak lint verifies statically), each
    begin event in the stream is eventually followed by one of its end
    events — runtime evidence that nothing leaked under chaos.

Usage:
    JAX_PLATFORMS=cpu python -m tools.chaos_run                 # all
    JAX_PLATFORMS=cpu python -m tools.chaos_run -s kill_mid_decode
    JAX_PLATFORMS=cpu python -m tools.chaos_run --seed 7 --list

Each scenario is also importable (tests/test_chaos.py runs the cheap ones
in tier-1); a scenario returns a metrics dict and raises AssertionError on
any invariant violation.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

PAGE = 32
PROMPT = [(i * 37) % 251 + 1 for i in range(70)]  # spans 2 full pages

_TINY = None


def _tiny():
    """Tiny model arch+params, built once per process (CLI runs several
    scenarios; each builds its own replicas over the SHARED weight tree)."""
    global _TINY
    if _TINY is None:
        import jax

        from localai_tpu.models import get_arch
        from localai_tpu.models.llama import init_params

        cfg = get_arch("tiny")
        _TINY = (cfg, init_params(cfg, jax.random.key(0)))
    return _TINY


def _ecfg(**kw):
    from localai_tpu.engine.engine import EngineConfig

    defaults = dict(
        max_slots=2, max_seq=256, min_prefill_bucket=32,
        kv_pages=16, kv_page_size=PAGE,
        prefix_cache_entries=4, prefix_cache_min=PAGE,
        prefix_admit_async_compile=False,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


def _build(roles, **client_kw):
    from localai_tpu.cluster import ClusterClient, build_local_replicas
    from localai_tpu.engine.tokenizer import ByteTokenizer

    cfg, params = _tiny()
    replicas = build_local_replicas(
        cfg, params, ByteTokenizer(cfg.vocab_size), n=len(roles),
        engine_cfg=_ecfg(), roles=list(roles))
    client_kw.setdefault("gauge_refresh_s", 0.0)
    client = ClusterClient(replicas, **client_kw)
    return replicas, client


def _stop_all(replicas):
    for rep in replicas:
        rep.engine.stop()
        rep.engine.params = None
        rep.engine.cache = None


def _submit_streams(client, n_req, n_new, prompt_fn=None):
    """Submit n_req streaming requests, waiting for each one's FIRST token
    before the next submit (every request is live when a fault lands, and
    the load gauges spread traffic over the fleet)."""
    from localai_tpu.engine.engine import GenRequest

    handles, firsts = [], []
    for i in range(n_req):
        prompt = (prompt_fn(i) if prompt_fn
                  else [(i * 13 + j) % 251 + 1 for j in range(40)])
        h = client.submit(GenRequest(prompt_ids=prompt,
                                     max_new_tokens=n_new, ignore_eos=True))
        handles.append(h)
        firsts.append(h._q.get(timeout=60.0))
    assert all(ev.kind == "token" for ev in firsts), firsts
    return handles, firsts


def _drain_all(handles, firsts=None, timeout=120.0):
    """Drain every handle on its own thread. Returns ({i: [events]}, hung);
    the zero-hung-callers invariant is `assert not hung`."""
    results: dict[int, list] = {}

    def drain(i, h, first):
        evs = [first] if first is not None else []
        for ev in h:
            evs.append(ev)
        results[i] = evs

    firsts = firsts or [None] * len(handles)
    threads = [threading.Thread(target=drain, args=(i, h, f), daemon=True,
                                name=f"chaos-drain-{i}")
               for i, (h, f) in enumerate(zip(handles, firsts))]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout
    for t in threads:
        t.join(timeout=max(0.1, deadline - time.monotonic()))
    hung = [t.name for t in threads if t.is_alive()]
    return results, hung


def _assert_all_terminal(results, n_req, kinds=("done",)):
    assert len(results) == n_req, (len(results), n_req)
    for i, evs in results.items():
        assert evs and evs[-1].kind in kinds, (i, evs[-1:])


def _member_transitions(events):
    """[(rid, old_state, new_state)] from member_state journal events."""
    from localai_tpu.cluster import MEMBER_STATES

    out = []
    for e in events:
        if e["event"] == "member_state":
            old = (MEMBER_STATES[int(e["b"])] if e["b"] >= 0 else None)
            out.append((e["rid"], old, MEMBER_STATES[int(e["a"])]))
    return out


def assert_breaker_probe_discipline(events):
    """≤ 1 breaker probe per half-open window, from journal events: between
    consecutive breaker_open events (or open→close) for one breaker there
    is at most one breaker_probe — the half-open gate admits a single
    in-flight probe and every probe outcome closes or re-opens the window."""
    windows: dict[str, int] = {}
    for e in events:
        rid = e["rid"]
        if e["event"] == "breaker_open":
            windows[rid] = 0
        elif e["event"] == "breaker_probe":
            assert rid in windows, f"probe with no open window on {rid}"
            windows[rid] += 1
            assert windows[rid] <= 1, \
                f"{windows[rid]} probes in one half-open window on {rid}"
        elif e["event"] == "breaker_close":
            windows.pop(rid, None)


def assert_journal_balance(events):
    """Registry-driven lifecycle balance (ISSUE 20): for every protocol
    with a `journal=(begin, ends)` declaration in tools/lint/resources.py,
    each begin event is eventually followed by one of its end events for
    the same rid. This is the runtime mirror of the resource-leak lint —
    the static pass proves no code path drops the resource, this proves no
    scenario actually did."""
    from tools.lint.resources import JOURNAL_BALANCE

    names = {e["event"] for e in events}
    for pid, (begin, ends) in JOURNAL_BALANCE.items():
        if begin not in names:
            continue  # scenario never exercised this protocol
        open_by_rid: dict[str, int] = {}
        for e in events:
            rid = e["rid"]
            if e["event"] == begin:
                assert open_by_rid.get(rid, 0) == 0, (
                    f"{pid}: second {begin} on {rid} while the previous "
                    f"one is still unresolved")
                open_by_rid[rid] = 1
            elif e["event"] in ends:
                # Ends without a begin are legal (breaker_open fires on a
                # plain trip too) — the check is begin ⇒ eventual end.
                open_by_rid[rid] = 0
        stuck = [rid for rid, n in open_by_rid.items() if n]
        assert not stuck, (
            f"{pid}: {begin} never followed by any of {ends} for {stuck}")


# --------------------------------------------------------------------- #
# Scenarios
# --------------------------------------------------------------------- #


def kill_mid_decode(seed=99):
    """Kill one replica's engine loop while every request is streaming:
    all requests reroute to the survivor and deliver their full length."""
    from localai_tpu.testing import faults

    replicas, client = _build(["mixed", "mixed"])
    try:
        n_req, n_new = 4, 32
        handles, firsts = _submit_streams(client, n_req, n_new)
        loop_idents = {
            r.engine._thread.ident for r in replicas
            if any(s is not None and len(s.generated) <= n_new - 8
                   for s in r.engine.slots)
        }
        assert loop_idents, "no replica mid-stream at fault activation"
        script = faults.ChaosScript(seed=seed, threads=loop_idents, phases=[
            faults.ChaosPhase("engine_loop", after_calls=0, rate=1.0,
                              max_faults=1)])
        with faults.active(script):
            deadline = time.monotonic() + 60.0
            while (not any(r.engine.is_dead for r in replicas)
                   and time.monotonic() < deadline):
                time.sleep(0.005)
        assert any(r.engine.is_dead for r in replicas), \
            "injected loop death never landed"
        results, hung = _drain_all(handles, firsts)
        assert not hung, f"hung callers: {hung}"
        _assert_all_terminal(results, n_req)
        for i, evs in results.items():
            n_toks = sum(1 for ev in evs if ev.kind == "token")
            assert n_toks == n_new, (i, n_toks)
        assert client.m_reroutes >= 1
        assert not client._pending, "records leaked past their terminals"
        events = client.scheduler.journal_events()
        assert_journal_balance(events)
        trans = _member_transitions(events)
        assert any(new == "dead" for _, _, new in trans), trans
        return {"reroutes": client.m_reroutes,
                "dead": sum(r.engine.is_dead for r in replicas)}
    finally:
        _stop_all(replicas)


def slow_gauge(seed=5):
    """Gauge scrapes flap BELOW the death threshold: routing continues on
    last-good gauges, nobody is marked dead, every request completes."""
    from localai_tpu.testing import faults

    replicas, client = _build(["mixed", "mixed"])
    try:
        # Warm-up promotes both joiners to active before the flap starts.
        client.generate(PROMPT, max_new_tokens=2, ignore_eos=True)
        thr = client.scheduler.gauge_fail_threshold
        script = faults.ChaosScript(seed=seed, phases=[
            faults.ChaosPhase("gauge_scrape", after_calls=0, rate=1.0,
                              max_faults=thr - 1)])
        with faults.active(script):
            handles, firsts = _submit_streams(client, 4, 16)
            results, hung = _drain_all(handles, firsts)
        assert not hung, f"hung callers: {hung}"
        _assert_all_terminal(results, 4)
        assert script.exhausted(), "the gauge flap never fired"
        events = client.scheduler.journal_events()
        assert_journal_balance(events)
        assert any(e["event"] == "fault_gauge_scrape" for e in events)
        trans = _member_transitions(events)
        assert not any(new == "dead" for _, _, new in trans), \
            f"sub-threshold gauge flaps killed a replica: {trans}"
        assert all(not r.engine.is_dead for r in replicas)
        return {"flaps": sum(p.fired for p in script.phases)}
    finally:
        _stop_all(replicas)


def partition_during_transfer(seed=1234):
    """Network partition while a KV span is in flight: the prefill→decode
    handoff degrades to recompute-on-decode — same bytes, no hung caller."""
    from localai_tpu.testing import faults

    replicas, client = _build(["prefill", "decode"])
    try:
        falls0 = client.m_handoff_fallbacks
        script = faults.ChaosScript(seed=seed, phases=[
            faults.ChaosPhase("span_transfer", after_calls=0, rate=1.0,
                              max_faults=2)])
        with faults.active(script):
            text, ev = client.generate(PROMPT, max_new_tokens=8,
                                       ignore_eos=True)
        assert ev.kind == "done" and len(text) > 0
        assert client.m_handoff_fallbacks == falls0 + 1
        # Recovery: the partition healed — the next handoff lands and
        # produces exactly what the recompute fallback produced.
        text2, ev2 = client.generate(PROMPT, max_new_tokens=8,
                                     ignore_eos=True)
        assert ev2.kind == "done" and text2 == text
        assert client.m_handoffs >= 1
        assert not client._pending
        return {"fallbacks": client.m_handoff_fallbacks - falls0,
                "handoffs": client.m_handoffs}
    finally:
        _stop_all(replicas)


def join_under_load(seed=0):
    """A replica joins while requests stream: it walks joining → active on
    its first successful gauge scrape and becomes routable, without
    perturbing in-flight streams."""
    from localai_tpu.cluster import build_local_replicas
    from localai_tpu.engine.tokenizer import ByteTokenizer

    cfg, params = _tiny()
    replicas, client = _build(["mixed"])
    joiner = None
    try:
        handles, firsts = _submit_streams(client, 2, 24)
        [joiner] = build_local_replicas(
            cfg, params, ByteTokenizer(cfg.vocab_size), n=1,
            engine_cfg=_ecfg(), roles=["mixed"], name_prefix="joiner")
        client.replicas.append(joiner)
        client.scheduler.add_replica(
            joiner.name, target=joiner, role=joiner.role,
            gauge_fn=joiner.gauges)
        assert client.scheduler.state(joiner.name) == "joining"
        client.scheduler.refresh(force=True)
        assert client.scheduler.state(joiner.name) == "active"
        # Routable: a pick excluding the incumbent lands on the joiner.
        assert client.scheduler.pick([], exclude=("r0",)) == joiner.name
        results, hung = _drain_all(handles, firsts)
        assert not hung, f"hung callers: {hung}"
        _assert_all_terminal(results, 2)
        # New traffic reaches the joiner's engine.
        before = joiner.engine.m_prompt_tokens
        h2, f2 = _submit_streams(client, 3, 8)
        r2, hung2 = _drain_all(h2, f2)
        assert not hung2 and len(r2) == 3
        events = client.scheduler.journal_events()
        assert_journal_balance(events)
        trans = _member_transitions(events)
        assert (joiner.name, None, "joining") in trans, trans
        assert (joiner.name, "joining", "active") in trans, trans
        return {"joiner_prompt_tokens":
                joiner.engine.m_prompt_tokens - before}
    finally:
        _stop_all(replicas)
        if joiner is not None:
            _stop_all([joiner])


def drain_under_load(seed=0):
    """Drain a replica mid-stream: no NEW admissions land on it, in-flight
    streams finish, its span affinity moves to the survivor, and leave()
    removes it once in-flight hits zero."""
    replicas, client = _build(["mixed", "mixed"])
    try:
        # Establish affinity + traffic on both replicas.
        handles, firsts = _submit_streams(client, 4, 24)
        sched = client.scheduler
        # The victim must HOLD affinity (so the handoff is observable) —
        # prefer one that is also mid-stream.
        snap = sorted(sched.snapshot(),
                      key=lambda s: (s["affinity_spans_held"] > 0,
                                     s["inflight"]), reverse=True)
        assert snap[0]["affinity_spans_held"] > 0, snap
        victim = snap[0]["name"]
        veng = next(r for r in replicas if r.name == victim).engine
        admitted0 = veng.m_prompt_tokens
        assert sched.begin_drain(victim)
        assert sched.state(victim) == "draining"
        # New work: every admission must land on the survivor.
        h2, f2 = _submit_streams(client, 3, 8)
        results, hung = _drain_all(handles + h2, firsts + f2)
        assert not hung, f"hung callers: {hung}"
        _assert_all_terminal(results, 7)
        assert veng.m_prompt_tokens == admitted0, \
            "a drained replica admitted new work"
        snap = {s["name"]: s for s in sched.snapshot()}
        assert snap[victim]["inflight"] == 0
        assert snap[victim]["affinity_spans_held"] == 0, \
            "drain left affinity behind"
        events = sched.journal_events()
        assert_journal_balance(events)
        handed = [e for e in events if e["event"] == "affinity_handoff"]
        assert handed and handed[0]["rid"] == victim, events
        # Graceful exit completes now that in-flight is zero.
        assert sched.leave(victim) == "removed"
        assert victim not in sched.names()
        trans = _member_transitions(events)
        assert any(t == (victim, "active", "draining") for t in trans), trans
        return {"victim": victim,
                "spans_handed": int(handed[0]["a"])}
    finally:
        _stop_all(replicas)


def grammar_replay(seed=0):
    """Mid-stream replica death under a grammar constraint: the survivor
    replays the emitted tokens through a fresh grammar machine and the
    greedy output is byte-identical to the no-fault run — and valid."""
    from localai_tpu.engine.engine import GenRequest
    from localai_tpu.functions.jsonschema import GrammarConstraint
    from localai_tpu.testing import faults

    schema = {"type": "object",
              "properties": {"a": {"type": "integer"},
                             "b": {"type": "boolean"}},
              "required": ["a", "b"]}
    n_new = 120

    def req():
        return GenRequest(prompt_ids=[10, 20, 30], max_new_tokens=n_new,
                          temperature=0.0,
                          grammar=GrammarConstraint(schema))

    # No-fault oracle on a fresh cluster.
    replicas, client = _build(["mixed", "mixed"])
    try:
        h = client.submit(req())
        want, wev = h.result()
        assert wev.kind == "done", wev
        json.loads(want)
    finally:
        _stop_all(replicas)

    replicas, client = _build(["mixed", "mixed"])
    try:
        h = client.submit(req())
        first = h._q.get(timeout=60.0)
        assert first.kind == "token", first
        # Exactly one engine is serving it — kill that loop.
        serving = [r for r in replicas
                   if any(s is not None for s in r.engine.slots)]
        assert serving, "request not live on any replica"
        idents = {r.engine._thread.ident for r in serving}
        script = faults.ChaosScript(seed=seed + 99, threads=idents, phases=[
            faults.ChaosPhase("engine_loop", after_calls=0, rate=1.0,
                              max_faults=1)])
        with faults.active(script):
            deadline = time.monotonic() + 60.0
            while (not any(r.engine.is_dead for r in replicas)
                   and time.monotonic() < deadline):
                time.sleep(0.005)
        assert any(r.engine.is_dead for r in replicas)
        results, hung = _drain_all([h], [first])
        assert not hung, f"hung callers: {hung}"
        evs = results[0]
        assert evs[-1].kind == "done", evs[-1]
        got = "".join(ev.text for ev in evs if ev.kind == "token")
        assert got == want, (got, want)
        json.loads(got)  # no grammar-invalid bytes ever reached the caller
        assert client.m_grammar_replays >= 1
        events = client.scheduler.journal_events()
        assert_journal_balance(events)
        assert any(e["event"] == "reroute_replay" for e in events), events
        return {"replays": client.m_grammar_replays, "bytes": len(got)}
    finally:
        _stop_all(replicas)


def breaker_window(seed=0):
    """Circuit-breaker probe discipline without engines: a flapping remote
    trips the breaker; journal events prove ≤ 1 probe per half-open
    window and recovery closes it."""
    from localai_tpu.cluster import BreakerOpen, CircuitBreaker
    from localai_tpu.observe.journal import EventJournal

    journal = EventJournal(capacity=256)

    def hook(event, a=0.0):
        journal.stage(event, rid="peer", a=a)

    clock = {"t": 0.0}
    br = CircuitBreaker(name="peer", failure_threshold=2, reset_s=1.0,
                        on_event=hook, clock=lambda: clock["t"])
    # Trip it.
    for _ in range(2):
        br.record_failure()
    assert br.state == "open"
    refused = 0
    for _ in range(5):  # refused while open — no probes before reset_s
        if not br.allow():
            refused += 1
    assert refused == 5
    # Half-open: exactly one probe per window; a failed probe re-opens.
    clock["t"] = 1.1
    assert br.allow() is True      # the single probe
    assert br.allow() is False     # second caller refused in-window
    br.record_failure()            # probe failed → re-open
    assert br.state == "open"
    clock["t"] = 2.2
    assert br.allow() is True
    br.record_success()            # probe succeeded → closed
    assert br.state == "closed"
    events = journal.snapshot()
    assert_breaker_probe_discipline(events)
    assert_journal_balance(events)
    kinds = [e["event"] for e in events]
    assert kinds.count("breaker_open") == 2
    assert kinds.count("breaker_probe") == 2
    assert kinds.count("breaker_close") == 1
    return {"refused": br.m_refused, "probes": br.m_probes}


SCENARIOS = {
    "kill_mid_decode": kill_mid_decode,
    "slow_gauge": slow_gauge,
    "partition_during_transfer": partition_during_transfer,
    "join_under_load": join_under_load,
    "drain_under_load": drain_under_load,
    "grammar_replay": grammar_replay,
    "breaker_window": breaker_window,
}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="run cluster chaos scenarios and assert invariants")
    ap.add_argument("-s", "--scenario", action="append", default=None,
                    choices=sorted(SCENARIOS), help="run only this scenario "
                    "(repeatable; default: all)")
    ap.add_argument("--seed", type=int, default=None,
                    help="override each scenario's default fault seed")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    args = ap.parse_args(argv)
    if args.list:
        for name, fn in SCENARIOS.items():
            print(f"{name}: {fn.__doc__.strip().splitlines()[0]}")
        return 0
    names = args.scenario or list(SCENARIOS)
    failed = []
    for name in names:
        fn = SCENARIOS[name]
        t0 = time.monotonic()
        try:
            out = fn() if args.seed is None else fn(seed=args.seed)
            print(f"PASS {name} ({time.monotonic() - t0:.1f}s): "
                  f"{json.dumps(out)}")
        except AssertionError as e:
            failed.append(name)
            print(f"FAIL {name} ({time.monotonic() - t0:.1f}s): {e}")
    if failed:
        print(f"{len(failed)}/{len(names)} scenario(s) failed: "
              + ", ".join(failed))
        return 1
    print(f"all {len(names)} scenario(s) passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
