#!/usr/bin/env python
"""DEPRECATED shim — the three passes that lived here (attr-init,
metric-counters, lock-discipline) moved into the lint framework at
tools/lint/ (ISSUE 5). Use:

    python -m tools.lint                  # all passes
    python -m tools.lint --pass attr-init,metric-counters,lock-discipline

This file keeps the original function signatures for callers pinned to the
old API (tests/test_engine_attrs.py predates the framework) and will be
removed once nothing imports it.
"""

from __future__ import annotations

import ast
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.lint.passes.attr_init import uninitialized_reads  # noqa: E402
from tools.lint.passes.lock_discipline import check_class_locks  # noqa: E402
from tools.lint.passes.metric_counters import uninitialized_counters  # noqa: E402

DEFAULT_PATH = os.path.join(_REPO, "localai_tpu", "engine", "engine.py")


def _load(path: str, class_name: str):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    classes = {
        n.name: n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
    }
    cls = classes.get(class_name)
    if cls is None:
        raise SystemExit(f"class {class_name} not found in {path}")
    return cls, classes


def check_class(path: str, class_name: str) -> list[tuple[str, str, int]]:
    """[(attr, method, line)] read-but-never-constructed attributes."""
    cls, classes = _load(path, class_name)
    return uninitialized_reads(cls, classes)


def check_metric_counters(path: str, class_name: str) -> list[tuple[str, int]]:
    """[(attr, line)] m_* counters metrics() reads but __init__ never set."""
    cls, classes = _load(path, class_name)
    return uninitialized_counters(cls, classes)


def check_lock_discipline(
    path: str, class_name: str, lock_attr: str = "_pending_lock"
) -> list[tuple[str, str, int]]:
    """[(attr, method, line)] unlocked rebinds of lock-protected state."""
    cls, _ = _load(path, class_name)
    return check_class_locks(cls, lock_attr)


def main(argv: list[str]) -> int:
    print(
        "NOTE: tools/check_engine_attrs.py is a deprecation shim — "
        "use `python -m tools.lint` (docs/STATIC_ANALYSIS.md)",
        file=sys.stderr,
    )
    path = argv[1] if len(argv) > 1 else DEFAULT_PATH
    class_name = argv[2] if len(argv) > 2 else "Engine"
    findings = check_class(path, class_name)
    for attr, method, line in findings:
        print(
            f"{path}:{line}: self.{attr} read in {class_name}.{method}() "
            f"but never assigned in __init__ (loop-thread AttributeError "
            f"waiting to happen — BENCH_r05 rc=124 was exactly this)"
        )
    counter_findings = check_metric_counters(path, class_name)
    for attr, line in counter_findings:
        print(
            f"{path}:{line}: metric counter self.{attr} read in "
            f"{class_name}.metrics() but never initialized in __init__ — "
            f"the scrape would AttributeError on a fresh engine"
        )
    lock_findings = check_lock_discipline(path, class_name)
    for attr, method, line in lock_findings:
        print(
            f"{path}:{line}: self.{attr} rebound in {class_name}.{method}() "
            f"WITHOUT _pending_lock, but it is read under that lock "
            f"elsewhere — cross-thread torn read (ISSUE 4 lock discipline)"
        )
    if findings or counter_findings or lock_findings:
        return 1
    print(f"{class_name}: all attribute reads covered by construction")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
