#!/usr/bin/env python
"""Static pass: flag `self.x` attributes READ somewhere in a class but never
assigned during construction.

The exact bug class that killed BENCH_r05 (rc=124): the engine-loop
admission path read `self._admit_hold_start` / `self._last_submit_t` before
any code path had ever assigned them — the loop thread died of
AttributeError on the first idle admission and every caller hung on a token
queue forever. Python has no compiler to catch this; this AST pass does.

Rule: every attribute the class loads (`self.x` in Load context, or reads
via `self.x += ...`) must be assigned by construction — in `__init__`, in a
method `__init__` (transitively) calls on self, or at class level — or be a
method/property of the class. Attributes probed with `hasattr(self, "x")`
anywhere in the class are exempt (lazy-init caches declare themselves that
way).

Usage:
    python tools/check_engine_attrs.py [path] [ClassName]
defaults to localai_tpu/engine/engine.py Engine. Exit 1 on findings; also
wired into tier-1 via tests/test_engine_attrs.py.
"""

from __future__ import annotations

import ast
import os
import sys

DEFAULT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "localai_tpu", "engine", "engine.py",
)


def _self_name(fn: ast.FunctionDef) -> str | None:
    """The instance-receiver arg name, or None for static/class methods
    (a classmethod's first arg binds the type — attribute reads on it
    resolve against class attributes, out of scope here)."""
    for dec in fn.decorator_list:
        name = dec.id if isinstance(dec, ast.Name) else getattr(dec, "attr", "")
        if name in ("staticmethod", "classmethod"):
            return None
    args = fn.args.posonlyargs + fn.args.args
    return args[0].arg if args else None


def _attr_stores(fn: ast.FunctionDef) -> set[str]:
    """Names assigned as `self.x = ...` (tuple targets included) anywhere in
    the function. AugAssign does NOT count — `self.x += 1` requires a prior
    binding, i.e. it is a read."""
    me = _self_name(fn)
    out: set[str] = set()
    if me is None:
        return out
    for node in ast.walk(fn):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            for tt in ast.walk(t):
                if (isinstance(tt, ast.Attribute)
                        and isinstance(tt.value, ast.Name)
                        and tt.value.id == me):
                    out.add(tt.attr)
    return out


def _attr_reads(fn: ast.FunctionDef) -> dict[str, int]:
    """{attr: first line} for `self.x` loads (and AugAssign reads)."""
    me = _self_name(fn)
    out: dict[str, int] = {}
    if me is None:
        return out
    for node in ast.walk(fn):
        attr = None
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == me):
            if isinstance(node.ctx, ast.Load):
                attr = node.attr
            elif isinstance(node.ctx, ast.Store):
                continue
        if isinstance(node, ast.AugAssign):
            t = node.target
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == me):
                attr = t.attr
        if attr is not None:
            out.setdefault(attr, node.lineno)
    return out


def _self_calls(fn: ast.FunctionDef) -> set[str]:
    """Method names invoked as `self.m(...)` — the __init__ call graph."""
    me = _self_name(fn)
    out: set[str] = set()
    if me is None:
        return out
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == me):
            out.add(node.func.attr)
    return out


def _hasattr_probes(cls: ast.ClassDef) -> set[str]:
    """Attr names checked via hasattr(self, "x") anywhere in the class."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "hasattr" and len(node.args) == 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)):
            out.add(node.args[1].value)
    return out


def check_class(path: str, class_name: str) -> list[tuple[str, str, int]]:
    """Returns [(attr, method, line)] for attributes read but never
    assigned during construction."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    cls = next(
        (n for n in ast.walk(tree)
         if isinstance(n, ast.ClassDef) and n.name == class_name),
        None,
    )
    if cls is None:
        raise SystemExit(f"class {class_name} not found in {path}")
    methods = {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    class_level: set[str] = set()
    for n in cls.body:
        if isinstance(n, ast.Assign):
            class_level |= {t.id for t in n.targets if isinstance(t, ast.Name)}
        elif isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name):
            class_level.add(n.target.id)

    # Attributes assigned during construction: __init__ plus every method it
    # (transitively) calls on self.
    assigned: set[str] = set(class_level) | set(methods)
    seen: set[str] = set()
    frontier = ["__init__"]
    while frontier:
        name = frontier.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        assigned |= _attr_stores(methods[name])
        frontier.extend(_self_calls(methods[name]))

    exempt = _hasattr_probes(cls)
    findings: list[tuple[str, str, int]] = []
    for name, fn in methods.items():
        for attr, line in sorted(_attr_reads(fn).items(), key=lambda kv: kv[1]):
            if attr in assigned or attr in exempt:
                continue
            if attr.startswith("__") and attr.endswith("__"):
                continue  # dunders resolve on the type
            findings.append((attr, name, line))
    return sorted(set(findings), key=lambda f: f[2])


def check_metric_counters(path: str, class_name: str) -> list[tuple[str, int]]:
    """Stricter companion pass for the metrics surface: every `self.m_*`
    counter the class's `metrics()` method reads must be UNCONDITIONALLY
    initialized during construction (__init__ or a method it transitively
    calls). The general pass already catches never-assigned reads; this one
    exists because metric counters are the repeat offender (the BENCH_r05
    rc=124 class) — they get added at a dispatch site, read in metrics(),
    and the init line is what gets forgotten. Returns [(attr, line)]."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    cls = next(
        (n for n in ast.walk(tree)
         if isinstance(n, ast.ClassDef) and n.name == class_name),
        None,
    )
    if cls is None:
        raise SystemExit(f"class {class_name} not found in {path}")
    methods = {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    if "metrics" not in methods:
        return []
    init_assigned: set[str] = set()
    seen: set[str] = set()
    frontier = ["__init__"]
    while frontier:
        name = frontier.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        init_assigned |= _attr_stores(methods[name])
        frontier.extend(_self_calls(methods[name]))
    exempt = _hasattr_probes(cls)
    return sorted(
        (attr, line)
        for attr, line in _attr_reads(methods["metrics"]).items()
        if attr.startswith("m_")
        and attr not in init_assigned
        and attr not in exempt
    )


def check_lock_discipline(
    path: str, class_name: str, lock_attr: str = "_pending_lock"
) -> list[tuple[str, str, int]]:
    """Third pass (ISSUE 4): attributes READ inside `with self.<lock_attr>:`
    somewhere in the class must never be REBOUND (`self.x = ...` /
    `self.x += ...`) outside such a block at runtime — the lock exists
    because another thread reads that state, so an unlocked rebind is a
    torn-read waiting to happen (submit() and the loop thread share
    _pending exactly this way). Construction (__init__ plus everything it
    transitively calls on self) is exempt: no second thread exists yet.
    Returns [(attr, method, line)] for unlocked rebinds."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    cls = next(
        (n for n in ast.walk(tree)
         if isinstance(n, ast.ClassDef) and n.name == class_name),
        None,
    )
    if cls is None:
        raise SystemExit(f"class {class_name} not found in {path}")
    methods = {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }

    construction: set[str] = set()
    seen: set[str] = set()
    frontier = ["__init__"]
    while frontier:
        name = frontier.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        frontier.extend(_self_calls(methods[name]))
    construction = seen

    def _is_lock_with(node: ast.With, me: str) -> bool:
        for item in node.items:
            ctx = item.context_expr
            if (isinstance(ctx, ast.Attribute)
                    and isinstance(ctx.value, ast.Name)
                    and ctx.value.id == me and ctx.attr == lock_attr):
                return True
        return False

    reads_locked: set[str] = set()
    # [(attr, method, line, locked)] for every rebind of a self attribute.
    rebinds: list[tuple[str, str, int, bool]] = []

    for mname, fn in methods.items():
        me = _self_name(fn)
        if me is None:
            continue

        def walk(node: ast.AST, locked: bool, mname=mname, me=me) -> None:
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == me):
                if isinstance(node.ctx, ast.Load) and locked:
                    reads_locked.add(node.attr)
                elif isinstance(node.ctx, ast.Store):
                    rebinds.append((node.attr, mname, node.lineno, locked))
            child_locked = locked or (
                isinstance(node, ast.With) and _is_lock_with(node, me)
            )
            for child in ast.iter_child_nodes(node):
                walk(child, child_locked)

        walk(fn, False)

    # Method/property accesses under the lock are calls, not shared state.
    protected = reads_locked - set(methods) - {lock_attr}
    findings = [
        (attr, mname, line)
        for attr, mname, line, locked in rebinds
        if attr in protected and not locked and mname not in construction
    ]
    return sorted(set(findings), key=lambda f: f[2])


def main(argv: list[str]) -> int:
    path = argv[1] if len(argv) > 1 else DEFAULT_PATH
    class_name = argv[2] if len(argv) > 2 else "Engine"
    findings = check_class(path, class_name)
    for attr, method, line in findings:
        print(
            f"{path}:{line}: self.{attr} read in {class_name}.{method}() "
            f"but never assigned in __init__ (loop-thread AttributeError "
            f"waiting to happen — BENCH_r05 rc=124 was exactly this)"
        )
    counter_findings = check_metric_counters(path, class_name)
    for attr, line in counter_findings:
        print(
            f"{path}:{line}: metric counter self.{attr} read in "
            f"{class_name}.metrics() but never initialized in __init__ — "
            f"the scrape would AttributeError on a fresh engine"
        )
    lock_findings = check_lock_discipline(path, class_name)
    for attr, method, line in lock_findings:
        print(
            f"{path}:{line}: self.{attr} rebound in {class_name}.{method}() "
            f"WITHOUT _pending_lock, but it is read under that lock "
            f"elsewhere — cross-thread torn read (ISSUE 4 lock discipline)"
        )
    if findings or counter_findings or lock_findings:
        return 1
    print(f"{class_name}: all attribute reads covered by construction")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
