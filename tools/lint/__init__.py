"""localai-lint: repo-native multi-pass static analysis (ISSUE 5).

Usage:
    python -m tools.lint            # human output, exit 1 on findings
    python -m tools.lint --json     # machine output
    python -m tools.lint --list     # show the pass registry

See docs/STATIC_ANALYSIS.md for the pass catalogue, the incident each pass
encodes, and the suppression syntax (`# lint: ignore[pass-id] reason`).
"""

from __future__ import annotations

import os

from .core import (  # noqa: F401 — public API
    Finding,
    Pass,
    Repo,
    RunResult,
    apply_suppressions,
    run_passes,
    write_report,
)
from .passes import all_passes  # noqa: F401

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_repo(root: str = REPO_ROOT, only=None, skip=None,
             limit=None) -> RunResult:
    """Run the full registry over a repo checkout. `limit` (an iterable of
    repo-relative paths) narrows FILE-SCOPED passes to those files — the
    --since incremental mode; project-wide passes always run in full."""
    return run_passes(Repo(root, limit=limit), all_passes(),
                      only=only, skip=skip)


def changed_since(root: str, rev: str) -> list[str]:
    """Repo-relative paths changed vs a git rev (staged + unstaged +
    committed-after-rev), for --since. Raises on a bad rev."""
    import subprocess

    proc = subprocess.run(
        ["git", "diff", "--name-only", rev, "--"],
        cwd=root, capture_output=True, text=True, timeout=30,
    )
    if proc.returncode != 0:
        raise ValueError(
            f"git diff --name-only {rev!r} failed: {proc.stderr.strip()}"
        )
    return [ln.strip() for ln in proc.stdout.splitlines() if ln.strip()]
