"""localai-lint: repo-native multi-pass static analysis (ISSUE 5).

Usage:
    python -m tools.lint            # human output, exit 1 on findings
    python -m tools.lint --json     # machine output
    python -m tools.lint --list     # show the pass registry

See docs/STATIC_ANALYSIS.md for the pass catalogue, the incident each pass
encodes, and the suppression syntax (`# lint: ignore[pass-id] reason`).
"""

from __future__ import annotations

import os

from .core import (  # noqa: F401 — public API
    Finding,
    Pass,
    Repo,
    RunResult,
    apply_suppressions,
    run_passes,
    write_report,
)
from .passes import all_passes  # noqa: F401

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_repo(root: str = REPO_ROOT, only=None, skip=None) -> RunResult:
    """Run the full registry over a repo checkout."""
    return run_passes(Repo(root), all_passes(), only=only, skip=skip)
