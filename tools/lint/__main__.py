"""CLI: python -m tools.lint [--json] [--list] [--pass a,b] [--skip a,b]
[--root PATH] [--report FILE] [--since REV]. Exit 0 clean, 1 findings,
2 usage error.

--since REV lints only files changed vs the git rev (file-scoped passes;
cross-file passes still run in full over the shared call-graph/summary
cache) — the fast pre-commit mode the verify skill uses:
`python -m tools.lint --since HEAD`."""

from __future__ import annotations

import argparse
import json
import sys

from . import REPO_ROOT, changed_since, run_repo
from .core import write_report
from .passes import all_passes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.lint",
        description="localai-lint: repo-native multi-pass static analysis",
    )
    ap.add_argument("--root", default=REPO_ROOT, help="repo root to analyze")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--list", action="store_true", help="list registered passes")
    ap.add_argument("--pass", dest="only", default=None,
                    help="comma-separated pass ids to run (default: all)")
    ap.add_argument("--skip", default=None,
                    help="comma-separated pass ids to skip")
    ap.add_argument("--report", default=None,
                    help="write the LINT_rNN.json counts report here "
                         "(includes per-pass wall_time_ms)")
    ap.add_argument("--since", default=None, metavar="REV",
                    help="lint only files changed vs this git rev "
                         "(project-wide passes still run in full)")
    args = ap.parse_args(argv)

    if args.list:
        for p in all_passes():
            print(f"{p.id:16s} {p.description}")
        return 0

    only = args.only.split(",") if args.only else None
    skip = args.skip.split(",") if args.skip else None
    known = {p.id for p in all_passes()}
    for pid in (only or []) + (skip or []):
        if pid not in known:
            print(f"unknown pass id {pid!r} (see --list)", file=sys.stderr)
            return 2

    limit = None
    if args.since is not None:
        try:
            limit = changed_since(args.root, args.since)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2

    result = run_repo(args.root, only=only, skip=skip, limit=limit)
    if args.report:
        write_report(result, args.report)
    if args.json:
        print(json.dumps(result.to_json(), indent=1))
    else:
        for f in result.findings:
            print(f.render())
        n, s = len(result.active), len(result.suppressed)
        scope = (f" ({len(limit)} changed file(s) vs {args.since})"
                 if limit is not None else "")
        total_ms = sum(result.timings.values()) * 1000.0
        print(f"{len(result.pass_ids)} passes in {total_ms:.0f} ms{scope}: "
              f"{n} finding(s), {s} suppression(s)")
    return 0 if result.clean else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
