"""Shared AST helpers for class-level passes (migrated from
tools/check_engine_attrs.py, which is now a thin deprecation shim)."""

from __future__ import annotations

import ast
from typing import Optional

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def self_name(fn) -> Optional[str]:
    """The instance-receiver arg name, or None for static/class methods
    (a classmethod's first arg binds the type — attribute reads on it
    resolve against class attributes, out of scope here)."""
    for dec in fn.decorator_list:
        name = dec.id if isinstance(dec, ast.Name) else getattr(dec, "attr", "")
        if name in ("staticmethod", "classmethod"):
            return None
    args = fn.args.posonlyargs + fn.args.args
    return args[0].arg if args else None


def methods_of(cls: ast.ClassDef) -> dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body if isinstance(n, FunctionNode)}


def class_level_names(cls: ast.ClassDef) -> set[str]:
    out: set[str] = set()
    for n in cls.body:
        if isinstance(n, ast.Assign):
            out |= {t.id for t in n.targets if isinstance(t, ast.Name)}
        elif isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name):
            out.add(n.target.id)
    return out


def attr_stores(fn) -> set[str]:
    """Names assigned as `self.x = ...` (tuple targets included) anywhere in
    the function. AugAssign does NOT count — `self.x += 1` requires a prior
    binding, i.e. it is a read."""
    me = self_name(fn)
    out: set[str] = set()
    if me is None:
        return out
    for node in ast.walk(fn):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            for tt in ast.walk(t):
                if (isinstance(tt, ast.Attribute)
                        and isinstance(tt.value, ast.Name)
                        and tt.value.id == me):
                    out.add(tt.attr)
    return out


def attr_reads(fn) -> dict[str, int]:
    """{attr: first line} for `self.x` loads (and AugAssign reads)."""
    me = self_name(fn)
    out: dict[str, int] = {}
    if me is None:
        return out
    for node in ast.walk(fn):
        attr = None
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == me):
            if isinstance(node.ctx, ast.Load):
                attr = node.attr
            elif isinstance(node.ctx, ast.Store):
                continue
        if isinstance(node, ast.AugAssign):
            t = node.target
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == me):
                attr = t.attr
        if attr is not None:
            out.setdefault(attr, node.lineno)
    return out


def self_calls(fn) -> set[str]:
    """Method names invoked as `self.m(...)` — the intra-class call graph."""
    me = self_name(fn)
    out: set[str] = set()
    if me is None:
        return out
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == me):
            out.add(node.func.attr)
    return out


def hasattr_probes(cls: ast.ClassDef) -> set[str]:
    """Attr names checked via hasattr(self, "x") anywhere in the class."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "hasattr" and len(node.args) == 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)):
            out.add(node.args[1].value)
    return out


def construction_methods(methods: dict[str, ast.FunctionDef]) -> set[str]:
    """__init__ plus every method it (transitively) calls on self — no
    second thread exists while these run."""
    seen: set[str] = set()
    frontier = ["__init__"]
    while frontier:
        name = frontier.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        frontier.extend(self_calls(methods[name]))
    return seen


def construction_assigned(cls: ast.ClassDef,
                          module_classes: Optional[dict] = None) -> set[str]:
    """Attributes assigned during construction: class level, __init__, and
    every method __init__ transitively calls on self. Method/property names
    count (they resolve on the type). When `module_classes` ({name: node})
    is given, same-module base classes contribute their construction too
    (super().__init__ runs their assignments)."""
    methods = methods_of(cls)
    assigned = class_level_names(cls) | set(methods)
    for name in construction_methods(methods):
        assigned |= attr_stores(methods[name])
    if module_classes:
        for base in cls.bases:
            bname = base.id if isinstance(base, ast.Name) else getattr(base, "attr", "")
            bcls = module_classes.get(bname)
            if bcls is not None and bcls is not cls:
                assigned |= construction_assigned(bcls, module_classes)
    return assigned


def dotted_name(node: ast.AST) -> str:
    """'jnp.zeros' for Attribute/Name chains, '' for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""
