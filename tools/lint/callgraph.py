"""Project-wide call graph for interprocedural lint passes (ISSUE 8).

Every pass before this one was intraprocedural — and PRs 6-7 added exactly
the bug classes that live *between* functions: lock-order cycles across the
cluster scheduler / engine / manager threads, RNG keys consumed by helpers,
donated buffers read by a caller after a callee's dispatch. This module
gives passes a shared, cached view of "who calls whom" with just enough
type inference to resolve the call shapes this repo actually uses:

  self.m(...)            -> method of the enclosing class (same-module bases
                            included — super().__init__ chains resolve)
  self.attr.m(...)       -> method of the class assigned to self.attr in
                            construction (`self.x = ClassName(...)`, or via a
                            local whose type is known, or an annotation)
  local.m(...)           -> method of the local's inferred class
  func(...) / mod.f(...) -> same-module or imported project function;
                            ClassName(...) resolves to ClassName.__init__
  anything.m(...)        -> fallback: if exactly ONE indexed class defines a
                            method `m` AND `m` is not a ubiquitous container/
                            stdlib method name, that method (unique-name
                            heuristic — `x.add(...)` must never resolve to
                            WorkerRegistry.add just because x's type is
                            unknown; it is almost always a set)

Resolution returns CANDIDATES (possibly empty): passes must treat an
unresolved call as "unknown", never as "safe" or "unsafe" on its own.
Everything here is pure AST — no imports of the code under analysis — and
cached on the Repo like the module cache, so N passes pay for one build.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from . import astutil
from .core import Repo


# Method names shared with builtin containers / files / stdlib objects: the
# unique-name fallback must never claim these (a bare `x.pop()` is a dict,
# not the one indexed class that happens to define pop()).
COMMON_METHOD_NAMES = frozenset({
    "add", "append", "appendleft", "clear", "close", "copy", "count",
    "discard", "extend", "flush", "get", "index", "insert", "items", "join",
    "keys", "pop", "popleft", "popitem", "put", "read", "readline", "recv",
    "release", "acquire", "remove", "reverse", "run", "seek", "send", "set",
    "setdefault", "sort", "start", "stop", "tell", "update", "values",
    "wait", "write", "cancel", "result", "info", "debug", "warning",
    "error", "exception", "critical", "log", "mark", "list", "search",
    "match", "sub", "split", "strip", "encode", "decode", "format", "is_set",
})


@dataclasses.dataclass
class FuncDef:
    fid: str                 # "path::Class.method" or "path::func"
    path: str                # repo-relative
    cls: Optional[str]       # enclosing class name (None for module funcs)
    name: str                # bare function/method name
    node: ast.AST            # the FunctionDef/AsyncFunctionDef


def module_of(path: str) -> str:
    """Dotted module name for a repo-relative path."""
    mod = path[:-3] if path.endswith(".py") else path
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


class CallGraph:
    """Index + resolver over a set of target files. Build once per glob set
    (Repo caches instances via `Repo.callgraph`)."""

    def __init__(self, repo: Repo, paths: list[str]):
        self.repo = repo
        self.paths = list(paths)
        self.funcs: dict[str, FuncDef] = {}
        # (path, class) -> ClassDef; class method tables; base-class names
        self.classes: dict[tuple[str, str], ast.ClassDef] = {}
        self._methods: dict[tuple[str, str], dict[str, str]] = {}
        self._bases: dict[tuple[str, str], list[str]] = {}
        # method name -> [fid] across every indexed class (unique-name fallback)
        self.by_method: dict[str, list[str]] = {}
        # per-module name -> ("func", fid) | ("class", (path, cls)) | ("mod", path)
        self._module_names: dict[str, dict[str, tuple]] = {}
        # (path, cls) -> {attr: set[(path, cls)]} inferred self.attr types
        self._attr_types: dict[tuple[str, str], dict[str, set]] = {}
        self._mod_to_path = {module_of(p): p for p in repo.files("**/*.py")}
        self._resolve_memo: dict[tuple, tuple[str, ...]] = {}
        for p in self.paths:
            self._index_file(p)
        self._by_node = {id(fd.node): fd for fd in self.funcs.values()}
        for p in self.paths:
            self._module_names[p] = self._build_namespace(p)
        for p in self.paths:
            self._infer_attr_types(p)
        # Local-type entries computed DURING attr-type inference cached
        # without the self-attr-alias rule (`j = self._journal`) — flush so
        # post-build consumers (summaries, passes) recompute with the full
        # attr-type map available.
        cache = getattr(repo, "_ltype_cache", None)
        if cache is not None:
            cache.clear()

    # ---------------- indexing ---------------- #

    def _index_file(self, path: str) -> None:
        tree = self.repo.tree(path)
        for node in tree.body:
            if isinstance(node, astutil.FunctionNode):
                fid = f"{path}::{node.name}"
                self.funcs[fid] = FuncDef(fid, path, None, node.name, node)
        # Classes are indexed at ANY depth (ISSUE 15): the HTTP handler
        # classes this repo spawns threads into (`class Proxy(Base...)`
        # inside FederationRouter._build, the server's RequestHandlerImpl)
        # are defined inside builder functions, and the thread-model passes
        # need their methods as roots. First definition of a name wins on
        # the rare same-file collision.
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            key = (path, node.name)
            if key in self.classes:
                continue
            self.classes[key] = node
            self._bases[key] = [
                b.id if isinstance(b, ast.Name) else getattr(b, "attr", "")
                for b in node.bases
            ]
            table: dict[str, str] = {}
            for m in node.body:
                if isinstance(m, astutil.FunctionNode):
                    fid = f"{path}::{node.name}.{m.name}"
                    self.funcs[fid] = FuncDef(fid, path, node.name, m.name, m)
                    table[m.name] = fid
                    self.by_method.setdefault(m.name, []).append(fid)
            self._methods[key] = table

    def _build_namespace(self, path: str) -> dict[str, tuple]:
        """Name -> target for module-level symbols AND imports (function-level
        imports included: the engine's lazy-import idiom would otherwise hide
        half the graph; shadowing across scopes is rare enough to accept)."""
        ns: dict[str, tuple] = {}
        tree = self.repo.tree(path)
        for node in tree.body:
            if isinstance(node, astutil.FunctionNode):
                ns[node.name] = ("func", f"{path}::{node.name}")
            elif isinstance(node, ast.ClassDef):
                ns[node.name] = ("class", (path, node.name))
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    tgt = self._mod_to_path.get(alias.name)
                    if tgt:
                        ns[alias.asname or alias.name.split(".")[0]] = ("mod", tgt)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = module_of(path).split(".")
                    base = base[: len(base) - node.level]
                    src = ".".join(base + ([node.module] if node.module else []))
                else:
                    src = node.module or ""
                src_path = self._mod_to_path.get(src)
                for alias in node.names:
                    bound = alias.asname or alias.name
                    sub = self._mod_to_path.get(f"{src}.{alias.name}")
                    if sub:
                        ns[bound] = ("mod", sub)
                        continue
                    if not src_path:
                        continue
                    if (src_path, alias.name) in self.classes:
                        ns[bound] = ("class", (src_path, alias.name))
                    elif f"{src_path}::{alias.name}" in self.funcs:
                        ns[bound] = ("func", f"{src_path}::{alias.name}")
        return ns

    # ---------------- type inference ---------------- #

    def _type_of_expr(self, path: str, node: ast.AST,
                      local_types: dict[str, set]) -> set:
        """Possible (path, cls) classes an expression evaluates to."""
        ns = self._module_names.get(path, {})
        if isinstance(node, ast.IfExp):
            # `EventJournal(n) if enabled else None` — the engine's
            # feature-gated attr idiom: union of both arms.
            return (self._type_of_expr(path, node.body, local_types)
                    | self._type_of_expr(path, node.orelse, local_types))
        if isinstance(node, ast.Call):
            name = astutil.dotted_name(node.func)
            if not name:
                return set()
            head, _, rest = name.partition(".")
            ent = ns.get(head)
            if ent is None:
                return set()
            if ent[0] == "class" and not rest:
                return {ent[1]}
            if ent[0] == "mod" and rest and "." not in rest:
                if (ent[1], rest) in self.classes:
                    return {(ent[1], rest)}
            return set()
        if isinstance(node, ast.Name):
            return set(local_types.get(node.id, ()))
        return set()

    def _annotation_types(self, path: str, ann: ast.AST) -> set:
        """Class candidates named anywhere inside an annotation (handles
        Optional[X], "X" strings, x.Y chains)."""
        out: set = set()
        ns = self._module_names.get(path, {})
        for sub in ast.walk(ann):
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                name = sub.value.split(".")[-1].strip("'\" ")
            if not name:
                continue
            ent = ns.get(name)
            if ent and ent[0] == "class":
                out.add(ent[1])
        return out

    def local_types(self, path: str, fn) -> dict[str, set]:
        """{local name: {(path, cls)}} from constructor calls, parameter
        annotations, and (second pass) the RETURN annotations of resolvable
        calls — `lm = self.get(name)` types lm when get() is annotated.
        Candidates accumulate; resolution tolerates supersets. Cached on
        the Repo by node identity (AST nodes are shared through the Repo
        tree cache), so the N pass-specific CallGraphs pay once."""
        cache = getattr(self.repo, "_ltype_cache", None)
        if cache is None:
            cache = self.repo._ltype_cache = {}
        if id(fn) in cache:
            return cache[id(fn)]
        types: dict[str, set] = {}
        args = fn.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if a.annotation is not None:
                t = self._annotation_types(path, a.annotation)
                if t:
                    types[a.arg] = set(t)
        fd = self._by_node.get(id(fn))
        me = astutil.self_name(fn) if fd is not None and fd.cls else None
        assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)]
        for _round in range(2):
            for node in assigns:
                t = self._type_of_expr(path, node.value, types)
                if (not t and me is not None
                        and isinstance(node.value, ast.Attribute)
                        and isinstance(node.value.value, ast.Name)
                        and node.value.value.id == me):
                    # `j = self._journal` — the local carries the attr's
                    # inferred type (the engine's local-alias idiom).
                    t = set(self._attr_types.get(
                        (path, fd.cls), {}).get(node.value.attr, ()))
                if not t and fd is not None and isinstance(node.value, ast.Call):
                    # Bypass the memo: these resolutions run with PARTIAL
                    # type maps mid-build and must not poison later lookups.
                    for fid in self._resolve_uncached(fd, node.value, types):
                        callee = self.funcs.get(fid)
                        ret = getattr(callee.node, "returns", None) if callee else None
                        if ret is not None:
                            t = t | self._annotation_types(callee.path, ret)
                if t:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            types.setdefault(tgt.id, set()).update(t)
        cache[id(fn)] = types
        return types

    def _infer_attr_types(self, path: str) -> None:
        for (p, cname), cls in list(self.classes.items()):
            if p != path:
                continue
            attrs: dict[str, set] = {}
            for m in cls.body:
                if not isinstance(m, astutil.FunctionNode):
                    continue
                me = astutil.self_name(m)
                if me is None:
                    continue
                ltypes = self.local_types(path, m)
                for node in ast.walk(m):
                    tgt = None
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == me):
                                tgt = t.attr
                        val = node.value
                    elif (isinstance(node, ast.AnnAssign)
                          and isinstance(node.target, ast.Attribute)
                          and isinstance(node.target.value, ast.Name)
                          and node.target.value.id == me):
                        tgt = node.target.attr
                        attrs.setdefault(tgt, set()).update(
                            self._annotation_types(path, node.annotation))
                        val = node.value
                    else:
                        continue
                    if tgt is None or val is None:
                        continue
                    t = self._type_of_expr(path, val, ltypes)
                    if t:
                        attrs.setdefault(tgt, set()).update(t)
            self._attr_types[(path, cname)] = attrs

    # ---------------- lookup helpers ---------------- #

    def method_fid(self, path: str, cls: str, name: str) -> Optional[str]:
        """Method fid on a class, walking same-module bases (MRO-ish)."""
        seen: set[tuple[str, str]] = set()
        stack = [(path, cls)]
        while stack:
            key = stack.pop(0)
            if key in seen or key not in self._methods:
                continue
            seen.add(key)
            fid = self._methods[key].get(name)
            if fid:
                return fid
            for b in self._bases.get(key, []):
                ent = self._module_names.get(key[0], {}).get(b)
                if ent and ent[0] == "class":
                    stack.append(ent[1])
                elif (key[0], b) in self.classes:
                    stack.append((key[0], b))
        return None

    def class_init(self, key: tuple) -> Optional[str]:
        return self.method_fid(key[0], key[1], "__init__")

    # ---------------- call resolution ---------------- #

    def resolve(self, fd: FuncDef, call: ast.Call,
                local_types: Optional[dict] = None,
                local_defs: Optional[dict] = None) -> tuple[str, ...]:
        """Candidate fids for a call made inside fd. local_defs maps nested
        function names to their fids (caller-scoped). Memoized per call
        node — summaries and flow passes resolve the same sites."""
        memo_key = (fd.fid, id(call))
        cached = self._resolve_memo.get(memo_key)
        if cached is not None:
            return cached
        out = self._resolve_uncached(fd, call, local_types, local_defs)
        self._resolve_memo[memo_key] = out
        return out

    def _resolve_uncached(self, fd: FuncDef, call: ast.Call,
                          local_types: Optional[dict] = None,
                          local_defs: Optional[dict] = None) -> tuple[str, ...]:
        f = call.func
        path = fd.path
        ns = self._module_names.get(path, {})
        me = astutil.self_name(fd.node) if fd.cls else None

        if isinstance(f, ast.Name):
            if local_defs and f.id in local_defs:
                return (local_defs[f.id],)
            ent = ns.get(f.id)
            if ent:
                if ent[0] == "func":
                    return (ent[1],) if ent[1] in self.funcs else ()
                if ent[0] == "class":
                    init = self.class_init(ent[1])
                    return (init,) if init else ()
            if local_types and f.id in local_types:
                # calling an instance — __call__ is out of scope
                return ()
            return ()

        if not isinstance(f, ast.Attribute):
            # fn()() — a call of a call: resolve the inner call's return;
            # passes that care (donation) handle this shape themselves.
            return ()

        dotted = astutil.dotted_name(f)
        parts = dotted.split(".") if dotted else []
        mname = f.attr

        if me is not None and parts and parts[0] == me:
            if len(parts) == 2:
                fid = self.method_fid(path, fd.cls, mname)
                return (fid,) if fid else ()
            if len(parts) == 3:
                cands = []
                for key in self._attr_types.get((path, fd.cls), {}).get(parts[1], ()):
                    fid = self.method_fid(key[0], key[1], mname)
                    if fid:
                        cands.append(fid)
                if cands:
                    return tuple(sorted(set(cands)))
        elif len(parts) == 2:
            ent = ns.get(parts[0])
            if ent and ent[0] == "mod":
                fid = f"{ent[1]}::{mname}"
                if fid in self.funcs:
                    return (fid,)
                if (ent[1], mname) in self.classes:
                    init = self.class_init((ent[1], mname))
                    return (init,) if init else ()
                return ()
            if local_types and parts[0] in local_types:
                cands = []
                for key in local_types[parts[0]]:
                    fid = self.method_fid(key[0], key[1], mname)
                    if fid:
                        cands.append(fid)
                if cands:
                    return tuple(sorted(set(cands)))

        # Unique-method-name fallback: receiver type unknown, but only one
        # indexed class defines this method AND the name is distinctive.
        if mname in COMMON_METHOD_NAMES or len(mname) <= 3:
            return ()
        owners = self.by_method.get(mname, [])
        if len(owners) == 1:
            return (owners[0],)
        return ()


def callgraph_for(repo: Repo, globs: tuple[str, ...]) -> CallGraph:
    """Repo-cached CallGraph for a glob set (the 'cached alongside the
    module cache' contract — N passes share one build)."""
    cache = getattr(repo, "_callgraphs", None)
    if cache is None:
        cache = repo._callgraphs = {}
    key = tuple(sorted(globs))
    if key not in cache:
        cache[key] = CallGraph(repo, repo.files(*globs))
    return cache[key]
