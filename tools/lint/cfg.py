"""Exception-edge-aware per-function control-flow graph (ISSUE 20).

Every resource-lifecycle incident this repo has hit (the PR 19 breaker
probe-slot leak, the pick→begin_stream inflight window, the PR 1/PR 4
terminal-event hangs) lived on an exit path the AST walkers could not see:
a `raise` out of a handler, an exception edge at a may-raise call, a
`finally` that runs on five different continuations. This module builds the
graph those passes reason over:

- one node per simple statement, one branch node per `if`/`while`/`for`
  test, explicit ENTRY / EXIT / RAISE_EXIT nodes;
- `return` / `break` / `continue` edges routed through every pending
  `finally` (each abrupt continuation gets its own finally copy, so a
  witness path through a finally is line-accurate);
- exception edges: a `raise` statement, or a statement containing a call
  that MAY raise, gets edges to the enclosing try's handlers — and, when
  no except-all handler catches, onward to RAISE_EXIT. "May raise" is an
  injected predicate (`call_may_raise`): the resource passes wire it to the
  interprocedural may-raise fixpoint (tools.lint.summaries) plus the
  known-raiser table; inside a `try` with handlers EVERY call is treated as
  raising — wrapping a call in try/except is the programmer's own
  declaration that it can throw, and the handler paths are exactly where
  leaks hide;
- `with` bodies flow normally (the context manager's __exit__ runs on every
  unwind, so a with-managed acquisition can never leak — the protocol
  matcher in tools.lint.resources treats it as self-resolving).

Pure AST, no imports of analyzed code, cached per function on the Repo by
the consuming passes. Edge kinds: next true false loop except raise return
break continue finally case.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Optional

_SKIP_BODIES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# Builtins / container ops that cannot meaningfully raise here. The
# "every call inside a try raises into its handlers" rule needs this carve-
# out: `acquired.append(row)` between an acquire and its handler-resolve
# would otherwise fabricate an exception path on which the append "threw"
# before ownership was recorded. KeyError/IndexError out of these are
# programmer-error crashes, the same bucket as assert.
_SAFE_CALLS = frozenset({
    "append", "appendleft", "extend", "extendleft", "add", "discard",
    "clear", "popleft", "pop", "remove", "insert", "update", "setdefault",
    "get", "keys", "values", "items", "put", "len", "str", "repr", "int",
    "float", "bool", "list", "dict", "tuple", "set", "frozenset", "sorted",
    "min", "max", "sum", "abs", "enumerate", "zip", "range", "isinstance",
    "id", "monotonic", "time", "perf_counter", "is_set", "join", "split",
    "strip", "startswith", "endswith", "format",
})


def _call_last_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


@dataclasses.dataclass
class Node:
    idx: int
    kind: str                      # entry|exit|raise-exit|stmt|branch|join
    line: int
    stmt: Optional[ast.AST] = None  # the statement (or test owner) node
    test: Optional[ast.expr] = None  # branch nodes: the test expression


class CFG:
    """succ[i] = [(target idx, edge kind)]. `stmt_nodes` maps id(stmt) to
    every node built from that statement (finally bodies are duplicated per
    continuation, so one statement may own several nodes)."""

    def __init__(self) -> None:
        self.nodes: list[Node] = []
        self.succ: list[list[tuple[int, str]]] = []
        self.entry = 0
        self.exit = 0
        self.raise_exit = 0
        self.stmt_nodes: dict[int, list[int]] = {}

    def node(self, kind: str, line: int = 0, stmt: Optional[ast.AST] = None,
             test: Optional[ast.expr] = None) -> int:
        idx = len(self.nodes)
        self.nodes.append(Node(idx, kind, line, stmt, test))
        self.succ.append([])
        if stmt is not None:
            self.stmt_nodes.setdefault(id(stmt), []).append(idx)
        return idx

    def edge(self, src: int, dst: int, kind: str) -> None:
        if (dst, kind) not in self.succ[src]:
            self.succ[src].append((dst, kind))

    def preds(self) -> dict[int, list[tuple[int, str]]]:
        out: dict[int, list[tuple[int, str]]] = {i: [] for i in range(len(self.nodes))}
        for i, edges in enumerate(self.succ):
            for dst, kind in edges:
                out[dst].append((i, kind))
        return out


def _const_truth(test: ast.expr) -> Optional[bool]:
    if isinstance(test, ast.Constant):
        return bool(test.value)
    return None


class _Builder:
    def __init__(self, fn, call_may_raise: Callable[[ast.Call], bool]):
        self.fn = fn
        self.call_may_raise = call_may_raise
        self.cfg = CFG()
        self.cfg.entry = self.cfg.node("entry", getattr(fn, "lineno", 0))
        self.cfg.exit = self.cfg.node("exit")
        self.cfg.raise_exit = self.cfg.node("raise-exit")

    # ---------------- raising ---------------- #

    def _calls_in(self, stmt: ast.AST) -> list[ast.Call]:
        out = []
        stack = [stmt]
        while stack:
            n = stack.pop()
            if isinstance(n, _SKIP_BODIES) and n is not stmt:
                continue
            if isinstance(n, ast.Call):
                out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        return out

    def _stmt_may_raise(self, stmt: ast.AST, frames: list) -> bool:
        calls = [c for c in self._calls_in(stmt)
                 if _call_last_name(c) not in _SAFE_CALLS]
        if not calls:
            return False
        if any(fr["kind"] == "try_body" and fr["info"]["handlers"]
               for fr in frames):
            # Inside a try with handlers every call raises into them: the
            # try IS the programmer's may-raise declaration.
            return True
        return any(self.call_may_raise(c) for c in calls)

    def _raise_dests(self, frames: list) -> list[tuple[int, str]]:
        """Where an exception raised under `frames` lands: each enclosing
        try's handlers (stopping at an except-all), else RAISE_EXIT —
        threading pending finally bodies on the way out."""
        out: list[tuple[int, str]] = []
        pending: list[dict] = []  # finally infos, innermost first
        for fr in reversed(frames):
            info = fr["info"]
            if fr["kind"] == "try_body":
                for h in info["handlers"]:
                    out.append((self._through_finallys(pending, h, "except"),
                                "except"))
                if info["catch_all"]:
                    return out
                if info["final"]:
                    pending.append(info)
            elif fr["kind"] == "fin_scope":
                if info["final"]:
                    pending.append(info)
        out.append((self._through_finallys(pending, self.cfg.raise_exit,
                                           "raise"), "raise"))
        return out

    def _through_finallys(self, pending: list[dict], target: int,
                          kind: str) -> int:
        """Chain finally-body copies (innermost runs first) in front of
        `target`; returns the entry to jump to. One copy per (target, kind)
        per try — all raise sites through a try share it."""
        cur = target
        for info in reversed(pending):
            cur = self._finally_copy(info, cur, kind)
        return cur

    def _finally_copy(self, info: dict, cont: int, kind: str) -> int:
        key = (cont, kind)
        if key in info["cache"]:
            return info["cache"][key]
        anchor = self.cfg.node("join", info["line"])
        # Reserve the cache slot BEFORE building: a finally whose body
        # raises back through itself must not recurse forever.
        info["cache"][key] = anchor
        ends = self.build_stmts(info["final"], list(info["outer"]),
                                [(anchor, "finally")])
        for i, k in ends:
            self.cfg.edge(i, cont, kind)
        return anchor

    # ---------------- abrupt exits ---------------- #

    def _unwind_to(self, frames: list, stop: str) -> tuple[list[dict], Optional[dict]]:
        """(pending finallys, loop frame or None) walking out until `stop`
        ("loop" or "func")."""
        pending: list[dict] = []
        for fr in reversed(frames):
            if fr["kind"] in ("try_body", "fin_scope") and fr["info"]["final"]:
                pending.append(fr["info"])
            if stop == "loop" and fr["kind"] == "loop":
                return pending, fr
        return pending, None

    # ---------------- statements ---------------- #

    def build_stmts(self, stmts: list, frames: list,
                    preds: list[tuple[int, str]]) -> list[tuple[int, str]]:
        for stmt in stmts:
            preds = self.build_stmt(stmt, frames, preds)
            if not preds:
                break  # unreachable tail after return/raise/break/continue
        return preds

    def _connect(self, preds: list[tuple[int, str]], dst: int) -> None:
        for i, k in preds:
            self.cfg.edge(i, dst, k)

    def build_stmt(self, stmt, frames: list,
                   preds: list[tuple[int, str]]) -> list[tuple[int, str]]:
        cfg = self.cfg
        if isinstance(stmt, ast.If):
            n = cfg.node("branch", stmt.lineno, stmt, stmt.test)
            self._connect(preds, n)
            self._maybe_raise(n, stmt.test, frames)
            truth = _const_truth(stmt.test)
            out: list[tuple[int, str]] = []
            if truth is not False:
                out += self.build_stmts(stmt.body, frames, [(n, "true")])
            if stmt.orelse:
                if truth is not True:
                    out += self.build_stmts(stmt.orelse, frames, [(n, "false")])
            elif truth is not True:
                out.append((n, "false"))
            return out

        if isinstance(stmt, ast.While):
            head = cfg.node("branch", stmt.lineno, stmt, stmt.test)
            self._connect(preds, head)
            self._maybe_raise(head, stmt.test, frames)
            after = cfg.node("join", stmt.lineno)
            loop_fr = {"kind": "loop", "info": {"final": None},
                       "head": head, "after": after}
            truth = _const_truth(stmt.test)
            if truth is not False:
                ends = self.build_stmts(stmt.body, frames + [loop_fr],
                                        [(head, "true")])
                for i, k in ends:
                    cfg.edge(i, head, "loop")
            if truth is not True:
                tail = [(head, "false")]
                if stmt.orelse:
                    tail = self.build_stmts(stmt.orelse, frames, tail)
                self._connect(tail, after)
            return [(after, "next")]

        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            head = cfg.node("branch", stmt.lineno, stmt, None)
            self._connect(preds, head)
            self._maybe_raise(head, stmt.iter, frames)
            after = cfg.node("join", stmt.lineno)
            loop_fr = {"kind": "loop", "info": {"final": None},
                       "head": head, "after": after}
            ends = self.build_stmts(stmt.body, frames + [loop_fr],
                                    [(head, "true")])
            for i, k in ends:
                cfg.edge(i, head, "loop")
            tail = [(head, "false")]
            if stmt.orelse:
                tail = self.build_stmts(stmt.orelse, frames, tail)
            self._connect(tail, after)
            return [(after, "next")]

        if isinstance(stmt, ast.Try):
            info = {
                "handlers": [], "catch_all": False,
                "final": stmt.finalbody or None, "cache": {},
                "outer": list(frames), "line": stmt.lineno,
            }
            for h in stmt.handlers:
                info["handlers"].append(cfg.node("stmt", h.lineno, h))
                if h.type is None:
                    info["catch_all"] = True
                else:
                    names = {
                        (e.id if isinstance(e, ast.Name)
                         else getattr(e, "attr", ""))
                        for e in (h.type.elts if isinstance(h.type, ast.Tuple)
                                  else [h.type])
                    }
                    if names & {"Exception", "BaseException"}:
                        info["catch_all"] = True
            body_fr = {"kind": "try_body", "info": info}
            fin_fr = {"kind": "fin_scope", "info": info}
            body_ends = self.build_stmts(stmt.body, frames + [body_fr], preds)
            normal = self.build_stmts(stmt.orelse, frames + [fin_fr],
                                      body_ends)
            for hn, h in zip(info["handlers"], stmt.handlers):
                normal += self.build_stmts(h.body, frames + [fin_fr],
                                           [(hn, "next")])
            if stmt.finalbody:
                return self.build_stmts(stmt.finalbody, frames, normal)
            return normal

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            n = cfg.node("stmt", stmt.lineno, stmt)
            self._connect(preds, n)
            for item in stmt.items:
                self._maybe_raise(n, item.context_expr, frames)
            return self.build_stmts(stmt.body, frames, [(n, "next")])

        if isinstance(stmt, ast.Match):
            n = cfg.node("branch", stmt.lineno, stmt, stmt.subject)
            self._connect(preds, n)
            out: list[tuple[int, str]] = []
            for case in stmt.cases:
                out += self.build_stmts(case.body, frames, [(n, "case")])
            out.append((n, "false"))
            return out

        # ---- simple statements ---- #
        n = cfg.node("stmt", getattr(stmt, "lineno", 0), stmt)
        self._connect(preds, n)

        if isinstance(stmt, ast.Return):
            pending, _ = self._unwind_to(frames, "func")
            dst = self._through_finallys(pending, cfg.exit, "return")
            cfg.edge(n, dst, "return")
            self._maybe_raise(n, stmt.value, frames)
            return []
        if isinstance(stmt, ast.Raise):
            for dst, kind in self._raise_dests(frames):
                cfg.edge(n, dst, kind)
            return []
        if isinstance(stmt, ast.Break):
            pending, loop_fr = self._unwind_to(frames, "loop")
            if loop_fr is not None:
                dst = self._through_finallys(pending, loop_fr["after"],
                                             "break")
                cfg.edge(n, dst, "break")
            return []
        if isinstance(stmt, ast.Continue):
            pending, loop_fr = self._unwind_to(frames, "loop")
            if loop_fr is not None:
                dst = self._through_finallys(pending, loop_fr["head"],
                                             "continue")
                cfg.edge(n, dst, "continue")
            return []
        if isinstance(stmt, ast.Assert):
            # AssertionError is a programmer-error crash, not control flow
            # the resource passes track (mirrors the may-raise seed rule).
            return [(n, "next")]

        self._maybe_raise(n, stmt, frames)
        return [(n, "next")]

    def _maybe_raise(self, n: int, expr, frames: list) -> None:
        if expr is not None and self._stmt_may_raise(expr, frames):
            for dst, kind in self._raise_dests(frames):
                self.cfg.edge(n, dst, kind)

    def build(self) -> CFG:
        ends = self.build_stmts(self.fn.body, [], [(self.cfg.entry, "next")])
        self._connect(ends, self.cfg.exit)
        return self.cfg


def build_cfg(fn, call_may_raise: Optional[Callable[[ast.Call], bool]] = None
              ) -> CFG:
    """CFG for one FunctionDef/AsyncFunctionDef. `call_may_raise` decides
    which calls OUTSIDE a try get exception edges (None = none do); calls
    inside a try with handlers always raise into them."""
    return _Builder(fn, call_may_raise or (lambda c: False)).build()


def ast_parents(fn) -> dict[int, ast.AST]:
    """{id(child): parent} over a function body — the acquire-context
    seeding walk (which if/else arms dominate a statement) uses this."""
    out: dict[int, ast.AST] = {}
    stack = [fn]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
            if not (isinstance(child, _SKIP_BODIES) and child is not fn):
                stack.append(child)
    return out


def dominating_tests(fn, stmt) -> list[tuple[ast.expr, bool]]:
    """[(test expr, polarity)] for every enclosing `if` whose body (True)
    or orelse (False) lexically contains `stmt`. Seeds the path-consistency
    facts when an analysis starts mid-function at an acquire site."""
    parents = ast_parents(fn)
    out: list[tuple[ast.expr, bool]] = []
    node = stmt
    while id(node) in parents:
        parent = parents[id(node)]
        if isinstance(parent, ast.If):
            in_body = any(node is s or _contains(s, node) for s in parent.body)
            out.append((parent.test, in_body))
        elif isinstance(parent, ast.While):
            if any(node is s or _contains(s, node) for s in parent.body):
                out.append((parent.test, True))
        node = parent
    return out


def _contains(tree: ast.AST, target: ast.AST) -> bool:
    for sub in ast.walk(tree):
        if sub is target:
            return True
    return False
