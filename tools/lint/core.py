"""localai-lint core: pass registry, shared AST/module cache, suppressions.

Every incident class this repo has hit traces to something Python's compiler
cannot see (ISSUE 5): the engine loop died of an AttributeError on an
unassigned `self._admit_hold_start` (BENCH_r05 rc=124); cancelled requests
hung callers because a code path dropped a pending entry without posting a
terminal event (bitten in PR 1 *and* PR 4); allocator leaks needed randomized
churn to surface. This framework promotes the ad-hoc AST checks that caught
those classes into a registry of passes that runs in tier-1 on every PR.

Contracts:

- A pass is a `Pass` subclass with a stable `id`, a `description`, and a
  `run(repo) -> list[Finding]`. Passes are pure AST/text analyses — they must
  never import the code under analysis (tier-1 runs them in <10 s on CPU and
  they must work on broken code).
- Findings are suppressed in source with a REQUIRED reason:

      something_flagged()  # lint: ignore[pass-id] why this is actually fine

  on the finding's line, or on a standalone comment line directly above it.
  A suppression without a reason is itself a finding (pass id `lint`), so
  silence always has a written justification next to the code.
- Exit codes (CLI): 0 clean, 1 findings, 2 usage/internal error.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re
import time
from typing import Iterable, Optional

# Matches `# lint: ignore[pass-id] reason...` (reason may start with -, —, :).
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*ignore\[(?P<pid>[a-z0-9_-]+)\]\s*[-—:]?\s*(?P<reason>.*)$"
)


@dataclasses.dataclass
class Finding:
    pass_id: str
    path: str  # repo-relative
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""  # suppression reason when suppressed
    # Ordered witness path from acquisition to the exit that loses the
    # resource (ISSUE 20): `"file:line"` entries, abnormal edges annotated
    # `"file:line (except)"` etc. Stable in --json (dataclasses.asdict);
    # empty for passes that don't trace paths.
    witness: list = dataclasses.field(default_factory=list)

    def render(self) -> str:
        tag = " [suppressed: %s]" % self.reason if self.suppressed else ""
        out = f"{self.path}:{self.line}: [{self.pass_id}] {self.message}{tag}"
        if self.witness:
            out += "\n    witness: " + " -> ".join(self.witness)
        return out


class Repo:
    """Shared parse cache over the repository: each file is read and parsed
    at most once no matter how many passes inspect it."""

    def __init__(self, root: str, limit: Optional[Iterable[str]] = None):
        self.root = os.path.abspath(root)
        self._src: dict[str, str] = {}
        self._lines: dict[str, list[str]] = {}
        self._tree: dict[str, ast.Module] = {}
        self._files: dict[tuple, list[str]] = {}
        # --since incremental mode (ISSUE 8): when set, FILE-SCOPED passes
        # only analyze these repo-relative paths; project-wide passes
        # (cross-file invariants: lock-order, sharding-consistency,
        # config-drift, fault-sites) always see everything — their
        # call-graph/summary caches make the full view cheap.
        self.limit: Optional[set[str]] = (
            None if limit is None
            else {p.replace(os.sep, "/") for p in limit}
        )

    def in_scope(self, path: str) -> bool:
        """Should a file-scoped pass analyze this file under --since?"""
        return self.limit is None or path.replace(os.sep, "/") in self.limit

    def rel(self, path: str) -> str:
        return os.path.relpath(os.path.join(self.root, path), self.root)

    def abspath(self, path: str) -> str:
        return path if os.path.isabs(path) else os.path.join(self.root, path)

    def exists(self, path: str) -> bool:
        return os.path.exists(self.abspath(path))

    def files(self, *patterns: str) -> list[str]:
        """Repo-relative .py paths under root matching any glob pattern
        (patterns are matched against the relative path, '/'-separated).
        Cached per pattern set — several passes share the same globs."""
        if patterns in self._files:
            return self._files[patterns]
        out = []
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".claude", "node_modules")
            ]
            for fn in filenames:
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                rel = rel.replace(os.sep, "/")
                if any(fnmatch.fnmatch(rel, p) for p in patterns):
                    out.append(rel)
        self._files[patterns] = sorted(out)
        return self._files[patterns]

    def source(self, path: str) -> str:
        rel = path.replace(os.sep, "/")
        if rel not in self._src:
            with open(self.abspath(rel), encoding="utf-8") as f:
                self._src[rel] = f.read()
        return self._src[rel]

    def lines(self, path: str) -> list[str]:
        rel = path.replace(os.sep, "/")
        if rel not in self._lines:
            self._lines[rel] = self.source(rel).splitlines()
        return self._lines[rel]

    def tree(self, path: str) -> ast.Module:
        rel = path.replace(os.sep, "/")
        if rel not in self._tree:
            self._tree[rel] = ast.parse(self.source(rel), filename=rel)
        return self._tree[rel]

    def classes(self, path: str) -> dict[str, ast.ClassDef]:
        """All classes in a module (nested included), by name."""
        return {
            n.name: n
            for n in ast.walk(self.tree(path))
            if isinstance(n, ast.ClassDef)
        }

    def find_class(self, path: str, name: str) -> Optional[ast.ClassDef]:
        return self.classes(path).get(name)


class Pass:
    """Base class for a lint pass. Subclasses set `id` and `description`
    and implement run(). `default_on` lets future niche passes ship opt-in.
    `project_wide` passes check cross-file invariants and ignore the
    --since file limit (narrowing them would silently skip the invariant)."""

    id: str = ""
    description: str = ""
    default_on: bool = True
    project_wide: bool = False

    def run(self, repo: Repo) -> list[Finding]:  # pragma: no cover - abstract
        raise NotImplementedError

    def finding(self, path: str, line: int, message: str,
                witness: Optional[list] = None) -> Finding:
        return Finding(pass_id=self.id, path=path, line=line, message=message,
                       witness=list(witness or ()))


def _suppression_for(lines: list[str], line: int, pass_id: str):
    """Return (found, reason) for a suppression governing `line` (1-based):
    the marker may sit on the line itself or on a standalone comment line
    directly above. Reason may be empty (caller turns that into a finding)."""
    candidates = []
    if 1 <= line <= len(lines):
        candidates.append(lines[line - 1])
    # Bounds-checked above AND below: a pass may anchor a cross-file
    # relationship (e.g. a race's mutation site) to a line number that
    # doesn't exist in the finding's own file.
    if 2 <= line <= len(lines) + 1 and lines[line - 2].lstrip().startswith("#"):
        candidates.append(lines[line - 2])
    for text in candidates:
        m = _SUPPRESS_RE.search(text)
        if m and m.group("pid") == pass_id:
            return True, m.group("reason").strip()
    return False, ""


def apply_suppressions(repo: Repo, findings: list[Finding],
                       known_ids: Iterable[str]) -> list[Finding]:
    """Mark suppressed findings in place; returns extra framework findings
    (reasonless suppressions, unknown pass ids in markers)."""
    extra: list[Finding] = []
    known = set(known_ids) | {"lint"}
    checked_files: set[str] = set()
    for f in findings:
        try:
            lines = repo.lines(f.path)
        except OSError:
            continue
        found, reason = _suppression_for(lines, f.line, f.pass_id)
        if found:
            if not reason:
                extra.append(Finding(
                    pass_id="lint", path=f.path, line=f.line,
                    message=(
                        f"suppression of [{f.pass_id}] has no reason — "
                        "write WHY after the bracket: "
                        f"`# lint: ignore[{f.pass_id}] <reason>`"
                    ),
                ))
            else:
                f.suppressed, f.reason = True, reason
        checked_files.add(f.path)
    # Malformed / unknown-pass markers anywhere in files we already loaded.
    for path in sorted(checked_files):
        for i, text in enumerate(repo.lines(path), start=1):
            m = _SUPPRESS_RE.search(text)
            if m and m.group("pid") not in known:
                extra.append(Finding(
                    pass_id="lint", path=path, line=i,
                    message=f"suppression names unknown pass id "
                            f"{m.group('pid')!r} (known: {sorted(known)})",
                ))
    return extra


@dataclasses.dataclass
class RunResult:
    findings: list[Finding]  # all, suppressed included
    pass_ids: list[str]  # passes that ran
    # Per-pass wall time (seconds) — makes the tier-1 <10 s budget
    # attributable pass by pass (ISSUE 8 satellite).
    timings: dict = dataclasses.field(default_factory=dict)

    @property
    def active(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def clean(self) -> bool:
        return not self.active

    def by_pass(self) -> dict[str, dict[str, int]]:
        out = {pid: {"findings": 0, "suppressions": 0} for pid in self.pass_ids}
        for f in self.findings:
            slot = out.setdefault(
                f.pass_id, {"findings": 0, "suppressions": 0}
            )
            slot["suppressions" if f.suppressed else "findings"] += 1
        for pid, secs in self.timings.items():
            if pid in out:
                out[pid]["wall_time_ms"] = round(secs * 1000.0, 1)
        return out

    def to_json(self) -> dict:
        return {
            "clean": self.clean,
            "passes": self.by_pass(),
            "total_findings": len(self.active),
            "total_suppressions": len(self.suppressed),
            "findings": [dataclasses.asdict(f) for f in self.findings],
        }

    def report(self) -> dict:
        """The LINT_rNN.json contract: pass → findings/suppressions counts.
        Future PRs assert the suppression count only goes DOWN."""
        return {
            "clean": self.clean,
            "passes": self.by_pass(),
            "total_suppressions": len(self.suppressed),
        }


def run_passes(repo: Repo, passes: list[Pass],
               only: Optional[Iterable[str]] = None,
               skip: Optional[Iterable[str]] = None) -> RunResult:
    only_set = set(only) if only is not None else None
    skip_set = set(skip or ())
    selected = [
        p for p in passes
        if (only_set is None and p.default_on or
            only_set is not None and p.id in only_set)
        and p.id not in skip_set
    ]
    findings: list[Finding] = []
    timings: dict[str, float] = {}
    for p in selected:
        t0 = time.monotonic()
        findings.extend(p.run(repo))
        timings[p.id] = time.monotonic() - t0
    findings.extend(
        apply_suppressions(repo, findings, [p.id for p in passes])
    )
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return RunResult(findings=findings, pass_ids=[p.id for p in selected],
                     timings=timings)


def write_report(result: RunResult, path: str) -> None:
    with open(path, "w") as f:
        json.dump(result.report(), f, indent=1, sort_keys=True)
        f.write("\n")
