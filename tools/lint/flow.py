"""Path-sensitive-enough statement walker for consumption analyses.

The rng-key-reuse and donation-safety passes share a shape: a value is
CONSUMED at some statement (a key drawn from, a buffer donated) and any
later use of the SAME binding on any path is a bug — unless the name was
rebound in between. This walker provides the control-flow plumbing both
need, tuned for low false positives rather than completeness:

  - statements execute in order; a rebind starts a new GENERATION of the
    name, so `key, sub = jax.random.split(key)` consumes the old key and
    the follow-up uses the new one.
  - `if`/`try` forks the state per branch and merges with INTERSECTION of
    consumed sets (a value consumed on only one branch might never have
    been consumed at runtime — flagging a later single use would be a
    false positive; in-branch double consumption is still caught inside
    the fork).
  - loop bodies run TWICE: the second pass sees the first iteration's
    consumptions, which is exactly how "consumed every iteration without a
    rebind" bugs surface (same key drawn per step, same buffer donated per
    step).

Subclasses implement `handle_expr(node, state)` (record consumptions) and
`handle_assign(stmt, state)` (process value THEN rebind targets).
"""

from __future__ import annotations

import ast


class FlowState:
    """Generation counters + consumed-set per tracked name."""

    def __init__(self):
        self.gen: dict[str, int] = {}
        self.consumed: dict[tuple[str, int], int] = {}  # (name, gen) -> line
        self.tracked: set[str] = set()

    def copy(self) -> "FlowState":
        st = FlowState()
        st.gen = dict(self.gen)
        st.consumed = dict(self.consumed)
        st.tracked = set(self.tracked)
        return st

    def merge(self, a: "FlowState", b: "FlowState") -> None:
        """Join of two branch states, in place."""
        self.gen = {
            k: max(a.gen.get(k, 0), b.gen.get(k, 0))
            for k in set(a.gen) | set(b.gen)
        }
        self.consumed = {
            k: a.consumed[k] for k in set(a.consumed) & set(b.consumed)
        }
        self.tracked = a.tracked | b.tracked

    # -------- name lifecycle -------- #

    def track(self, name: str) -> None:
        self.tracked.add(name)
        self.gen.setdefault(name, 0)

    def rebind(self, name: str, still_tracked: bool) -> None:
        if name in self.tracked or still_tracked:
            self.gen[name] = self.gen.get(name, 0) + 1
        if still_tracked:
            self.tracked.add(name)
        else:
            self.tracked.discard(name)

    def consume(self, name: str, line: int):
        """Returns the first-consumption line when this is a REUSE of the
        current generation, else None (and records the consumption)."""
        if name not in self.tracked:
            return None
        key = (name, self.gen.get(name, 0))
        if key in self.consumed:
            return self.consumed[key]
        self.consumed[key] = line
        return None


class LinearFlow:
    """Drive exec_block over a function body. Subclasses provide
    handle_expr / handle_assign; findings accumulate in self.hits as
    (line, first_line, name) deduped tuples."""

    def __init__(self):
        self.hits: dict[tuple, tuple] = {}

    # -------- overridables -------- #

    def handle_expr(self, node: ast.AST, st: FlowState) -> None:
        raise NotImplementedError

    def handle_assign(self, stmt: ast.stmt, st: FlowState) -> None:
        raise NotImplementedError

    def handle_for_target(self, stmt: ast.stmt, st: FlowState) -> None:
        """Rebind loop targets; default drops them from tracking."""
        for sub in ast.walk(stmt.target):
            if isinstance(sub, ast.Name):
                st.rebind(sub.id, still_tracked=False)

    # -------- plumbing -------- #

    def exec_block(self, stmts: list, st: FlowState) -> None:
        for s in stmts:
            self.exec_stmt(s, st)

    def exec_stmt(self, stmt: ast.stmt, st: FlowState) -> None:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self.handle_assign(stmt, st)
        elif isinstance(stmt, ast.Expr):
            self.handle_expr(stmt.value, st)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.handle_expr(stmt.value, st)
        elif isinstance(stmt, ast.If):
            self.handle_expr(stmt.test, st)
            s_then, s_else = st.copy(), st.copy()
            self.exec_block(stmt.body, s_then)
            self.exec_block(stmt.orelse, s_else)
            st.merge(s_then, s_else)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.handle_expr(stmt.iter, st)
            self.handle_for_target(stmt, st)
            for _ in range(2):
                self.exec_block(stmt.body, st)
            self.exec_block(stmt.orelse, st)
        elif isinstance(stmt, ast.While):
            for _ in range(2):
                self.handle_expr(stmt.test, st)
                self.exec_block(stmt.body, st)
            self.exec_block(stmt.orelse, st)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.handle_expr(item.context_expr, st)
            self.exec_block(stmt.body, st)
        elif isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            s_body = st.copy()
            self.exec_block(stmt.body, s_body)
            merged = s_body
            for h in stmt.handlers:
                s_h = st.copy()
                self.exec_block(h.body, s_h)
                joined = FlowState()
                joined.merge(merged, s_h)
                merged = joined
            st.gen, st.consumed, st.tracked = (
                merged.gen, merged.consumed, merged.tracked,
            )
            self.exec_block(stmt.orelse, st)
            self.exec_block(stmt.finalbody, st)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass  # nested scopes are analyzed on their own
        elif isinstance(stmt, (ast.Raise, ast.Assert)):
            for v in (getattr(stmt, "exc", None), getattr(stmt, "test", None),
                      getattr(stmt, "msg", None)):
                if v is not None:
                    self.handle_expr(v, st)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        st.rebind(sub.id, still_tracked=False)
        else:
            for v in ast.iter_child_nodes(stmt):
                if isinstance(v, ast.expr):
                    self.handle_expr(v, st)

    def record(self, line: int, first: int, name: str) -> None:
        self.hits[(line, name)] = (line, first, name)
