"""Pass registry. Adding a pass = one module here + one entry in ALL_PASSES
(+ fixtures under tests/lint_fixtures/ — no pass ships untested)."""

from __future__ import annotations

from .attr_init import AttrInitPass
from .config_drift import ConfigDriftPass
from .counter_balance import CounterBalancePass
from .donation_safety import DonationSafetyPass
from .double_resolve import DoubleResolvePass
from .fault_sites import FaultSitesPass
from .handoff_escape import HandoffEscapePass
from .journal_events import JournalEventsPass
from .lock_discipline import LockDisciplinePass
from .lock_order import LockOrderPass
from .metric_counters import MetricCountersPass
from .net_call_deadline import NetCallDeadlinePass
from .page_refcount import PageRefcountPass
from .resource_leak import ResourceLeakPass
from .rng_key_reuse import RngKeyReusePass
from .sharding_consistency import ShardingConsistencyPass
from .shared_state_race import SharedStateRacePass
from .terminal_event import TerminalEventPass
from .thread_affinity import ThreadAffinityPass
from .trace_safety import TraceSafetyPass


def all_passes():
    """Fresh pass instances with default (repo) targets."""
    return [
        AttrInitPass(),
        MetricCountersPass(),
        LockDisciplinePass(),
        TraceSafetyPass(),
        TerminalEventPass(),
        PageRefcountPass(),
        ConfigDriftPass(),
        FaultSitesPass(),
        # Interprocedural passes (ISSUE 8): shared call graph + summaries.
        LockOrderPass(),
        RngKeyReusePass(),
        ShardingConsistencyPass(),
        DonationSafetyPass(),
        # Flight-recorder consistency (ISSUE 11): faults.SITES ↔ journal
        # fault event types, both directions.
        JournalEventsPass(),
        # Thread-model passes (ISSUE 15): thread-root reachability ×
        # attribute effect sets over the shared SummaryIndex.
        SharedStateRacePass(),
        ThreadAffinityPass(),
        HandoffEscapePass(),
        # Remote-call hardening (ISSUE 19): every outbound network call
        # states its deadline.
        NetCallDeadlinePass(),
        # Resource-lifecycle verification (ISSUE 20): exception-edge CFG ×
        # the declarative protocol registry (tools.lint.resources) — the
        # leak-on-error class the PR 19 breaker-slot incident belonged to.
        ResourceLeakPass(),
        DoubleResolvePass(),
        CounterBalancePass(),
    ]
