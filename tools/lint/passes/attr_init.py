"""attr-init: `self.x` read somewhere in a class but never assigned during
construction.

The exact bug class that killed BENCH_r05 (rc=124): the engine-loop admission
path read `self._admit_hold_start` / `self._last_submit_t` before any code
path had ever assigned them — the loop thread died of AttributeError on the
first idle admission and every caller hung on a token queue forever. Python
has no compiler to catch this; this AST pass does.

Rule: every attribute the class loads (`self.x` in Load context, or reads via
`self.x += ...`) must be assigned by construction — in `__init__`, in a
method `__init__` (transitively) calls on self, or at class level — or be a
method/property of the class. Attributes probed with `hasattr(self, "x")`
anywhere in the class are exempt (lazy-init caches declare themselves that
way).
"""

from __future__ import annotations

from .. import astutil
from ..core import Finding, Pass, Repo

DEFAULT_TARGETS = [
    ("localai_tpu/engine/engine.py", "Engine"),
    ("localai_tpu/server/manager.py", "ModelManager"),
    ("localai_tpu/federation/router.py", "WorkerRegistry"),
    ("localai_tpu/federation/router.py", "Federator"),
    ("localai_tpu/testing/faults.py", "FaultSchedule"),
    ("localai_tpu/cluster/scheduler.py", "ClusterScheduler"),
    ("localai_tpu/cluster/scheduler.py", "ClusterClient"),
    ("localai_tpu/cluster/replica.py", "ClusterEngine"),
    # Multi-host subsystem (ISSUE 13): the stream assembler and remote
    # replica are touched from dispatch pumps and scheduler refreshes —
    # the same cross-thread AttributeError class as the Engine.
    ("localai_tpu/cluster/replica.py", "RemoteReplica"),
    ("localai_tpu/cluster/netspan.py", "StreamAssembler"),
    ("localai_tpu/testing/multihost.py", "WorkerProc"),
    ("localai_tpu/parallel/sharding.py", "ShardingPlanError"),
    # Observability layer (ISSUE 11): the journal/trace structures are
    # touched from the engine loop and HTTP threads — an unassigned attr
    # here is the same loop-killing class as on the Engine.
    ("localai_tpu/observe/journal.py", "EventJournal"),
    ("localai_tpu/observe/trace.py", "RequestTrace"),
    ("localai_tpu/observe/trace.py", "TraceStore"),
]


def uninitialized_reads(cls, module_classes=None):
    """[(attr, method, line)] of self-attribute reads no construction path
    assigns. Function-level API kept for the check_engine_attrs shim."""
    assigned = astutil.construction_assigned(cls, module_classes)
    exempt = astutil.hasattr_probes(cls)
    found: list[tuple[str, str, int]] = []
    for mname, fn in astutil.methods_of(cls).items():
        for attr, line in sorted(
            astutil.attr_reads(fn).items(), key=lambda kv: kv[1]
        ):
            if attr in assigned or attr in exempt:
                continue
            if attr.startswith("__") and attr.endswith("__"):
                continue  # dunders resolve on the type
            found.append((attr, mname, line))
    return sorted(set(found), key=lambda f: f[2])


class AttrInitPass(Pass):
    id = "attr-init"
    description = (
        "self.x read but never assigned during construction "
        "(loop-thread AttributeError — the BENCH_r05 rc=124 class)"
    )

    def __init__(self, targets=None):
        self.targets = DEFAULT_TARGETS if targets is None else targets

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for path, class_name in self.targets:
            if not repo.exists(path) or not repo.in_scope(path):
                continue
            cls = repo.find_class(path, class_name)
            if cls is None:
                continue
            for attr, mname, line in uninitialized_reads(cls, repo.classes(path)):
                out.append(self.finding(
                    path, line,
                    f"self.{attr} read in {class_name}.{mname}() but "
                    f"never assigned during construction — an "
                    f"AttributeError waiting for the first code path "
                    f"that reads it before any writer ran",
                ))
        return out
