"""config-drift: the four config surfaces must agree.

A knob exists four times: a dataclass field (`EngineConfig` /
`ModelConfig` / `ApplicationConfig`), a YAML key (ModelConfig fields ARE
the YAML schema via `from_dict`), an optional `LOCALAI_*` env override, and
a row in docs/CONFIG.md. They drift independently — PR 3/4 each added knobs
in three places and documented a different subset. Checks:

D1  Every ModelConfig / ApplicationConfig field is documented in
    docs/CONFIG.md (mentioned in backticks or as a table row). Nested
    configs (parallel.*, template.*) count via their dotted spelling.
D2  Every first-column entry of a CONFIG.md table names a real field —
    rows for knobs that no longer exist must be deleted.
D3  Every LOCALAI_* env var the code reads appears in docs/CONFIG.md.
D4  Every LOCALAI_* name mentioned in docs or code comments is actually
    read somewhere (string constant in localai_tpu/) — otherwise the
    override is an orphan: users set it and nothing happens.
D5  Every field name shared by ModelConfig and EngineConfig is forwarded in
    the manager's EngineConfig(...) construction — a YAML knob that never
    reaches the engine is dead.
"""

from __future__ import annotations

import ast
import re

from ..core import Finding, Pass, Repo

ENGINE_PY = "localai_tpu/engine/engine.py"
MODEL_CFG_PY = "localai_tpu/config/model_config.py"
APP_CFG_PY = "localai_tpu/config/app_config.py"
MANAGER_PY = "localai_tpu/server/manager.py"
CONFIG_MD = "docs/CONFIG.md"
CODE_GLOBS = ["localai_tpu/**/*.py", "localai_tpu/*.py"]

_ENV_RE = re.compile(r"LOCALAI_[A-Z0-9_]+")
_TABLE_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|")
# Doc-only identifiers that are legitimately not config fields (table rows
# describing request-body/API params or structural examples).
_DOC_ROW_ALLOW = {"field", "backend", "options"}


def dataclass_fields(tree: ast.Module, class_name: str) -> dict[str, int]:
    """{field: line} of annotated assignments in a (data)class body."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            out = {}
            for n in node.body:
                if isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name):
                    if not n.target.id.isupper():  # skip class constants
                        out[n.target.id] = n.lineno
            return out
    return {}


class ConfigDriftPass(Pass):
    id = "config-drift"
    description = (
        "dataclass fields ↔ YAML keys ↔ LOCALAI_* env vars ↔ docs/CONFIG.md "
        "rows out of sync (undocumented, dead, or orphaned knobs)"
    )

    def __init__(self, engine_py=ENGINE_PY, model_cfg_py=MODEL_CFG_PY,
                 app_cfg_py=APP_CFG_PY, manager_py=MANAGER_PY,
                 config_md=CONFIG_MD, code_globs=None):
        self.engine_py = engine_py
        self.model_cfg_py = model_cfg_py
        self.app_cfg_py = app_cfg_py
        self.manager_py = manager_py
        self.config_md = config_md
        self.code_globs = CODE_GLOBS if code_globs is None else code_globs

    def _env_constants(self, repo: Repo) -> dict[str, tuple[str, int]]:
        """Env names that appear as string CONSTANTS in code (i.e. actually
        read/used): {name: (path, line)} of first sighting."""
        out: dict[str, tuple[str, int]] = {}
        for path in repo.files(*self.code_globs):
            for node in ast.walk(repo.tree(path)):
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    for m in _ENV_RE.finditer(node.value):
                        out.setdefault(m.group(0), (path, node.lineno))
        return out

    def _env_mentions(self, repo: Repo) -> dict[str, tuple[str, int]]:
        """Env names mentioned ANYWHERE in code text (comments/docstrings
        included): {name: (path, line)}."""
        out: dict[str, tuple[str, int]] = {}
        for path in repo.files(*self.code_globs):
            for i, text in enumerate(repo.lines(path), start=1):
                for m in _ENV_RE.finditer(text):
                    out.setdefault(m.group(0), (path, i))
        return out

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        if not (repo.exists(self.model_cfg_py) and repo.exists(self.config_md)):
            return out

        model_fields = dataclass_fields(repo.tree(self.model_cfg_py), "ModelConfig")
        parallel_fields = dataclass_fields(repo.tree(self.model_cfg_py), "ParallelConfig")
        template_fields = dataclass_fields(repo.tree(self.model_cfg_py), "TemplateConfig")
        app_fields = (dataclass_fields(repo.tree(self.app_cfg_py), "ApplicationConfig")
                      if repo.exists(self.app_cfg_py) else {})
        engine_fields = (dataclass_fields(repo.tree(self.engine_py), "EngineConfig")
                         if repo.exists(self.engine_py) else {})

        doc_text = repo.source(self.config_md)
        doc_lines = repo.lines(self.config_md)
        doc_backticked = set(re.findall(r"`([^`\n]+)`", doc_text))

        def documented(name: str) -> bool:
            if name in doc_backticked:
                return True
            # dotted/nested spellings and prose mentions
            return bool(re.search(
                r"(^|[^a-zA-Z0-9_])" + re.escape(name) + r"($|[^a-zA-Z0-9_])",
                doc_text,
            ))

        # D1: undocumented knobs.
        for fname, line in sorted(model_fields.items()):
            if fname == "options":
                continue  # free-form passthrough, documented as a section
            if not documented(fname):
                out.append(self.finding(
                    self.model_cfg_py, line,
                    f"ModelConfig.{fname} (a YAML key) is not documented in "
                    f"{self.config_md} — add a row",
                ))
        for prefix, fields in (("parallel", parallel_fields),
                               ("template", template_fields)):
            for fname, line in sorted(fields.items()):
                if not (documented(f"{prefix}.{fname}") or documented(fname)):
                    out.append(self.finding(
                        self.model_cfg_py, line,
                        f"{prefix}.{fname} (a YAML key) is not documented in "
                        f"{self.config_md} — add a row",
                    ))
        for fname, line in sorted(app_fields.items()):
            if not documented(fname):
                out.append(self.finding(
                    self.app_cfg_py, line,
                    f"ApplicationConfig.{fname} is not documented in "
                    f"{self.config_md} (application-level section)",
                ))

        # D2: dead doc rows.
        known = (set(model_fields) | set(app_fields) | set(engine_fields)
                 | {f"parallel.{f}" for f in parallel_fields}
                 | {f"template.{f}" for f in template_fields}
                 | set(parallel_fields) | set(template_fields))
        in_field_table = False
        for i, text in enumerate(doc_lines, start=1):
            stripped = text.strip()
            if stripped.startswith("|"):
                first_cell = stripped.strip("|").split("|")[0].strip()
                if first_cell.strip("`") in ("field", "---"):
                    # header / separator: tables whose first column is
                    # `field` document config keys; others (backend option
                    # tables etc.) are prose.
                    if first_cell.strip("`") == "field":
                        in_field_table = True
                    continue
            else:
                in_field_table = False
                continue
            m = _TABLE_ROW_RE.match(stripped)
            if not m or not in_field_table:
                continue
            # `embeddings: true` / `known_usecases: [...]` style rows name
            # the field before the colon.
            name = m.group(1).split(":")[0].strip()
            base = name.split(".")[0]
            if name in known or base in known or name in _DOC_ROW_ALLOW:
                continue
            if _ENV_RE.fullmatch(name):
                continue  # env rows are checked by D3/D4
            out.append(self.finding(
                self.config_md, i,
                f"doc table row `{name}` names no existing config field — "
                f"delete the row or fix the name",
            ))

        # D3/D4: env var surface.
        read = self._env_constants(repo)
        mentioned = self._env_mentions(repo)
        doc_envs = {m.group(0) for m in _ENV_RE.finditer(doc_text)}
        for name, (path, line) in sorted(read.items()):
            if name == "LOCALAI_":
                continue
            if name not in doc_envs:
                out.append(self.finding(
                    path, line,
                    f"env var {name} is read by code but not documented in "
                    f"{self.config_md}",
                ))
        for name in sorted(doc_envs - set(read)):
            if name == "LOCALAI_":
                continue
            line = next(
                (i for i, t in enumerate(doc_lines, start=1) if name in t), 1
            )
            out.append(self.finding(
                self.config_md, line,
                f"{self.config_md} documents env var {name} but no code "
                f"reads it — orphaned knob (setting it does nothing)",
            ))
        for name, (path, line) in sorted(mentioned.items()):
            if name in read or name == "LOCALAI_":
                continue
            out.append(self.finding(
                path, line,
                f"{name} appears in a comment/docstring but no code reads "
                f"it — orphaned env var claim",
            ))

        # D5: shared ModelConfig/EngineConfig fields must be forwarded by
        # the manager's EngineConfig(...) construction.
        shared = set(model_fields) & set(engine_fields)
        if shared and repo.exists(self.manager_py):
            forwarded: set[str] = set()
            ctor_line = 1
            for node in ast.walk(repo.tree(self.manager_py)):
                if (isinstance(node, ast.Call)
                        and getattr(node.func, "id", getattr(node.func, "attr", ""))
                        == "EngineConfig"):
                    ctor_line = node.lineno
                    forwarded |= {kw.arg for kw in node.keywords if kw.arg}
            for fname in sorted(shared - forwarded):
                out.append(self.finding(
                    self.manager_py, ctor_line,
                    f"ModelConfig.{fname} mirrors EngineConfig.{fname} but "
                    f"the manager's EngineConfig(...) construction does not "
                    f"forward it — the YAML knob is dead",
                ))
        return out
