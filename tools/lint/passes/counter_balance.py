"""counter-balance: paired `m_*_begin` / `m_*_end` counters must balance
on every path.

The observability cousin of resource-leak (ISSUE 20): a gauge implemented
as begin/end counter pairs (the journal/metrics idiom for windows —
in-flight work is `begin - end`) drifts permanently if any CFG path bumps
`begin` and exits without bumping `end`. The gauge then reads phantom
in-flight work forever; dashboards and the chaos harness's balance
assertions (tools/chaos_run.py) both go quietly wrong. Exception edges are
where this hides — the happy path always balances.

Rule: within one function, every `self.m_X_begin += …` must reach a
`self.m_X_end += …` on every CFG exit path (exception edges included).
Counter pairs split across functions (begin in submit, end in the
completion callback) are a different, handoff-shaped protocol and are
exempt: only functions touching BOTH sides are checked.
"""

from __future__ import annotations

import ast
import re

from .. import astutil
from ..core import Finding, Pass, Repo
from ..resources import (AcqSpec, Acquisition, FlowAnalysis, Protocol,
                         _local_exprs, _TokenInfo, cfg_for)
from ..summaries import DEFAULT_SUMMARY_GLOBS, summaries_for

_BEGIN_RE = re.compile(r"^(m_.+)_begin$")

_COUNTER_PROTO = Protocol(
    pid="counter-balance", what="begin/end counter window",
    acquires=(), strict=False,
)


class _CounterClassifier:
    """FlowAnalysis classifier for counter pairs: the 'resolve' is a store
    to the matching *_end attribute; nothing transfers or kills."""

    def __init__(self, me: str, end_attr: str):
        self.me = me
        self.end_attr = end_attr
        self.proto = _COUNTER_PROTO
        self.ti = _TokenInfo("always")
        self.acq_call = None

    def resolve_at(self, node):
        for expr in _local_exprs(node):
            for sub in ast.walk(expr):
                if (isinstance(sub, ast.Attribute)
                        and isinstance(sub.ctx, ast.Store)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == self.me
                        and sub.attr == self.end_attr):
                    return ("blanket", sub.lineno)
        return None

    def transfers_at(self, node) -> bool:
        return False

    def kills_token(self, node) -> bool:
        return False


def _begin_sites(fn, me: str):
    """[(stmt, begin attr)] for `self.m_X_begin += …` statements."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, (ast.AugAssign, ast.Assign)):
            continue
        targets = ([node.target] if isinstance(node, ast.AugAssign)
                   else node.targets)
        for t in targets:
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == me and _BEGIN_RE.match(t.attr)):
                out.append((node, t.attr))
    return out


def _mentions_attr(fn, me: str, attr: str) -> bool:
    for node in ast.walk(fn):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == me and node.attr == attr):
            return True
    return False


class CounterBalancePass(Pass):
    id = "counter-balance"
    description = (
        "m_*_begin counter bumped on a path that exits without the "
        "matching m_*_end (the gauge drifts permanently)"
    )

    def __init__(self, globs=None):
        self.globs = tuple(globs) if globs else DEFAULT_SUMMARY_GLOBS

    def run(self, repo: Repo) -> list[Finding]:
        index = summaries_for(repo, self.globs)
        out: list[Finding] = []
        for fid, fd in index.graph.funcs.items():
            if not repo.in_scope(fd.path):
                continue
            if "_begin" not in repo.source(fd.path):
                continue
            me = astutil.self_name(fd.node) if fd.cls else None
            if me is None:
                continue
            sites = _begin_sites(fd.node, me)
            if not sites:
                continue
            cfg = cfg_for(repo, index, fd)
            for stmt, begin_attr in sites:
                end_attr = _BEGIN_RE.match(begin_attr).group(1) + "_end"
                if not _mentions_attr(fd.node, me, end_attr):
                    continue  # cross-function pair: not this pass's protocol
                acq = Acquisition(
                    spec=AcqSpec(begin_attr, "always"),
                    protocol=_COUNTER_PROTO, stmt=stmt, call=None,
                    line=stmt.lineno, token=None)
                classifier = _CounterClassifier(me, end_attr)
                issues = FlowAnalysis(cfg, fd.path, fd.node, acq, classifier,
                                      mode="leak").run()
                for iss in issues:
                    out.append(self.finding(
                        fd.path, iss.line,
                        f"{fd.cls}.{fd.name}() bumps {begin_attr} here but "
                        f"a path reaching line {iss.exit_line} exits "
                        f"without bumping {end_attr} — the window gauge "
                        f"(begin − end) drifts permanently on that path",
                        witness=iss.witness,
                    ))
        return out
