"""donation-safety: a donated buffer read after the donating call.

The engine donates aggressively — every decode block, fused admission,
chunk program, swap/restore, and the RNG setter pass their cache/counts/
rngs/token buffers with `donate_argnums` so XLA reuses the HBM in place
(SNIPPETS.md [1][2]: donation is what makes steady-state serving fit).
The contract is one-way: after the call, the donated buffer is DELETED.
Reading it again raises "Array has been deleted" at best — and on some
paths silently computes on stale aliases at worst. The bug only bites on
the path that reads (an error fallback, a retry, a second loop iteration),
which is exactly where tests don't look.

Rule, per function: at every call of a callable known to donate (a local
`fn = jax.jit(..., donate_argnums=(...))`, a `@partial(jax.jit,
donate_argnums=...)` def, or a project builder whose summary says it
RETURNS such a callable — the interprocedural part, covering the engine's
`fn = self._get_block(...)` / `self._get_rng_set()(...)` idioms), the
expressions at the donated positional slots (plain locals or `self.attr`
chains; `*args` tuples built from literals are spliced) become CONSUMED.
Any later read of the same binding on any path — including passing it to
the next iteration's donating call — is a finding until a rebind. Only the
positions donated on EVERY path are claimed (the literal base tuple), so
conditionally-extended donate lists can't false-positive.
"""

from __future__ import annotations

import ast
from typing import Optional

from .. import astutil
from ..core import Finding, Pass, Repo
from ..flow import FlowState, LinearFlow
from ..summaries import DEFAULT_SUMMARY_GLOBS, summaries_for

DEFAULT_GLOBS = (
    "localai_tpu/engine/*.py",
    "localai_tpu/train/*.py",
)


def _literal_int_tuple(node: ast.AST) -> Optional[tuple[int, ...]]:
    if isinstance(node, ast.Tuple):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    return None


def _jit_donations(call: ast.Call,
                   lit_locals: dict[str, tuple[int, ...]]) -> Optional[tuple[int, ...]]:
    """Donated positions of a jax.jit(...) call with a literal (or
    literal-local) donate_argnums; None when absent/unknowable."""
    if astutil.dotted_name(call.func) not in ("jax.jit", "jit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        lit = _literal_int_tuple(kw.value)
        if lit is not None:
            return lit
        if isinstance(kw.value, ast.Name):
            return lit_locals.get(kw.value.id)
    return None


def _decorated_donations(fn) -> Optional[tuple[int, ...]]:
    """@partial(jax.jit, donate_argnums=(...)) / @jax.jit(donate_argnums=...)
    on a def."""
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        name = astutil.dotted_name(dec.func)
        inner = dec
        if name in ("partial", "functools.partial"):
            if not (dec.args and astutil.dotted_name(dec.args[0])
                    in ("jax.jit", "jit")):
                continue
        elif name not in ("jax.jit", "jit"):
            continue
        for kw in inner.keywords:
            if kw.arg == "donate_argnums":
                lit = _literal_int_tuple(kw.value)
                if lit is not None:
                    return lit
    return None


class _DonationFlow(LinearFlow):
    def __init__(self, pass_globs, repo, path, fn):
        super().__init__()
        self.repo = repo
        self.path = path
        self.fn = fn
        self.idx = summaries_for(repo, pass_globs)
        self.graph = self.idx.graph
        self.fd = self.graph._by_node.get(id(fn))
        self.ltypes = (self.graph.local_types(path, fn)
                       if self.fd is not None else {})
        self.me = astutil.self_name(fn) if self.fd and self.fd.cls else None
        self.donating: dict[str, tuple[int, ...]] = {}
        self.lit_tuples: dict[str, tuple[int, ...]] = {}
        self.arg_tuples: dict[str, list] = {}  # name -> [arg expr nodes]
        self.donate_line: dict[tuple[str, int], int] = {}

    # -------- expr keys -------- #

    def _expr_key(self, node: ast.AST) -> Optional[str]:
        """Trackable identity of an argument expression: a plain local name
        or a self.attr chain."""
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            dotted = astutil.dotted_name(node)
            if (dotted and self.me is not None
                    and dotted.startswith(self.me + ".")
                    and dotted.count(".") == 1):
                return dotted
        return None

    # -------- donation resolution -------- #

    def _call_donations(self, call: ast.Call) -> Optional[tuple[int, ...]]:
        f = call.func
        if isinstance(f, ast.Name):
            return self.donating.get(f.id)
        if isinstance(f, ast.Call):
            # self._get_rng_set()(rngs, ...) — the builder's return donates.
            if self.fd is not None:
                for fid in self.graph.resolve(self.fd, f, self.ltypes):
                    s = self.idx.summaries.get(fid)
                    if s and s.donates:
                        return s.donates
        return None

    def _positional_exprs(self, call: ast.Call) -> list:
        """Positional argument expressions with *tuple locals spliced;
        an unresolvable *star truncates (positions past it are unknown)."""
        out = []
        for a in call.args:
            if isinstance(a, ast.Starred):
                if (isinstance(a.value, ast.Name)
                        and a.value.id in self.arg_tuples):
                    out.extend(self.arg_tuples[a.value.id])
                    continue
                break  # unknown splice — stop mapping positions
            out.append(a)
        return out

    # -------- flow hooks -------- #

    def _read_check(self, node: ast.AST, st: FlowState) -> None:
        for sub in ast.walk(node):
            key = None
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                key = sub.id
            elif isinstance(sub, ast.Attribute) and isinstance(sub.ctx, ast.Load):
                key = self._expr_key(sub)
            if key is None or key not in st.tracked:
                continue
            gkey = (key, st.gen.get(key, 0))
            if gkey in st.consumed:
                self.record(sub.lineno, st.consumed[gkey], key)

    def handle_expr(self, node: ast.AST, st: FlowState) -> None:
        if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        # Reads first: args already donated by an EARLIER call get flagged
        # here (donating the same buffer twice included).
        self._read_check(node, st)
        for call in [n for n in ast.walk(node) if isinstance(n, ast.Call)]:
            pos = self._call_donations(call)
            if not pos:
                continue
            exprs = self._positional_exprs(call)
            for i in pos:
                if i >= len(exprs):
                    continue
                key = self._expr_key(exprs[i])
                if key is None:
                    continue
                st.track(key)
                st.consume(key, call.lineno)

    def handle_assign(self, stmt, st: FlowState) -> None:
        value = getattr(stmt, "value", None)
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        if value is not None:
            # Bookkeeping: literal int tuples, arg tuples, jitted locals.
            lit = _literal_int_tuple(value)
            for t in targets:
                if isinstance(t, ast.Name):
                    if lit is not None:
                        self.lit_tuples[t.id] = lit
                    if isinstance(value, ast.Tuple):
                        self.arg_tuples[t.id] = list(value.elts)
                    elif (isinstance(value, ast.BinOp)
                          and isinstance(value.op, ast.Add)
                          and isinstance(value.left, ast.Name)
                          and value.left.id in self.arg_tuples
                          and isinstance(value.right, ast.Tuple)):
                        self.arg_tuples[t.id] = (
                            self.arg_tuples[value.left.id]
                            + list(value.right.elts))
                    if isinstance(value, ast.Call):
                        don = _jit_donations(value, self.lit_tuples)
                        if don is None and self.fd is not None:
                            for fid in self.graph.resolve(
                                    self.fd, value, self.ltypes):
                                s = self.idx.summaries.get(fid)
                                if s and s.donates:
                                    don = s.donates
                                    break
                        if don:
                            self.donating[t.id] = don
            self.handle_expr(value, st)
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    st.rebind(sub.id, still_tracked=sub.id in st.tracked)
                elif isinstance(sub, ast.Attribute):
                    key = self._expr_key(sub)
                    if key is not None:
                        st.rebind(key, still_tracked=key in st.tracked)

    def exec_stmt(self, stmt, st):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            don = _decorated_donations(stmt)
            if don:
                self.donating[stmt.name] = don
            return
        super().exec_stmt(stmt, st)

    def run(self, st: FlowState) -> None:
        self.exec_block(self.fn.body, st)


class DonationSafetyPass(Pass):
    id = "donation-safety"
    description = (
        "buffer read after being donated to a jitted call "
        "(XLA deleted it — 'Array has been deleted' on the untested path)"
    )

    def __init__(self, globs=None):
        self.globs = tuple(DEFAULT_GLOBS if globs is None else globs)
        # Builder-return summaries come from the shared union index on
        # default scope.
        self.summary_globs = (DEFAULT_SUMMARY_GLOBS if globs is None
                              else self.globs)

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for path in repo.files(*self.globs):
            if not repo.in_scope(path):
                continue
            for node in ast.walk(repo.tree(path)):
                if not isinstance(node, astutil.FunctionNode):
                    continue
                walker = _DonationFlow(self.summary_globs, repo, path, node)
                walker.run(FlowState())
                for line, first, key in sorted(walker.hits.values()):
                    out.append(self.finding(
                        path, line,
                        f"{key!r} read after being DONATED to a jitted call "
                        f"at line {first} — donated buffers are deleted by "
                        f"XLA; rebind the call's result (or drop the "
                        f"donation) before touching it again",
                    ))
        return out
