"""double-resolve: one acquisition, two resolves on a single path.

The mirror image of resource-leak (ISSUE 20): `end_stream` called twice
for one reservation drives the scheduler's inflight gauge negative (it
clamps, silently corrupting least-loaded placement); a double
`_pages_release` under-refcounts a shared prefix block so a LIVE stream's
pages return to the free list. Both are harder to see in review than a
leak because each call looks correct in isolation.

Checked on the same exception-edge CFG and protocol registry as
resource-leak: after a token-matched resolve, a second token-matched
resolve of the SAME handle reachable on the same path is a finding.
Clamp-and-heal protocols (breaker `record_*`: legal to call without a
held probe, by design) declare `strict=False` in the registry and are
excluded; blanket resolves (`_pages_free` slot teardown) prune the path
instead of arming it — only a literal second resolve of the same token
fires.
"""

from __future__ import annotations

from .. import astutil
from ..core import Finding, Pass, Repo
from ..resources import (ADAPTER_PIN, KV_PAGES, LOCK_MANUAL, SCHED_INFLIGHT,
                         analyze_protocol, releasing_methods)
from ..summaries import DEFAULT_SUMMARY_GLOBS, summaries_for

DEFAULT_PROTOCOLS = (KV_PAGES, SCHED_INFLIGHT, ADAPTER_PIN, LOCK_MANUAL)


class DoubleResolvePass(Pass):
    id = "double-resolve"
    description = (
        "two resolves of one acquisition reachable on a single CFG path "
        "(double release / double end_stream)"
    )

    def __init__(self, globs=None, protocols=None):
        self.globs = tuple(globs) if globs else DEFAULT_SUMMARY_GLOBS
        self.protocols = tuple(protocols) if protocols else DEFAULT_PROTOCOLS

    def run(self, repo: Repo) -> list[Finding]:
        index = summaries_for(repo, self.globs)
        acquire_names = sorted({s.call for p in self.protocols
                                for s in p.acquires})
        hot_path: dict[str, bool] = {}
        releasing: dict[tuple, tuple] = {}
        out: list[Finding] = []
        for fid, fd in index.graph.funcs.items():
            if not repo.in_scope(fd.path):
                continue
            if fd.path not in hot_path:
                src = repo.source(fd.path)
                hot_path[fd.path] = any(n in src for n in acquire_names)
            if not hot_path[fd.path]:
                continue
            extra = ()
            if fd.cls is not None:
                key = (fd.path, fd.cls)
                if key not in releasing:
                    cls_node = index.graph.classes.get(key)
                    # Methods that transitively release (e.g. the engine's
                    # _resume_discard) prune like the primitives do.
                    releasing[key] = () if cls_node is None else tuple(
                        releasing_methods(astutil.methods_of(cls_node)))
                extra = releasing[key]
            for iss in analyze_protocol(repo, index, fd, self.protocols,
                                        mode="double",
                                        extra_blanket_resolves=extra):
                if iss.kind != "double":
                    continue
                proto = iss.protocol
                owner = f"{fd.cls}.{fd.name}" if fd.cls else fd.name
                out.append(self.finding(
                    fd.path, iss.exit_line,
                    f"{owner}() resolves the {proto.what} acquired at line "
                    f"{iss.line} twice on one path (first at line "
                    f"{iss.first_resolve}, again here) — the second "
                    f"{proto.pid} resolve corrupts the balance "
                    f"(double-release / double-end_stream class)",
                    witness=iss.witness,
                ))
        return out
