"""fault-sites: every fault-injection site name maps to a real hook call.

`LOCALAI_FAULTS=seed:N,sites:a|b` schedules injections per SITE NAME
(localai_tpu/testing/faults.py). `FaultSchedule` already validates requested
sites against `SITES`, but nothing validated `SITES` against reality: a site
listed there whose `faults.fire("...")` call was renamed or deleted would
silently never fire, and every schedule targeting it would "pass" while
testing nothing. Both directions are checked:

  * every name in `faults.SITES` has at least one `faults.fire("name")`
    call site in production code (localai_tpu/, tests excluded — a site
    that only tests can fire is equally dead);
  * every `fire(...)` call uses a literal site name present in `SITES`
    (a non-literal argument defeats static verification and is flagged).
"""

from __future__ import annotations

import ast

from .. import astutil
from ..core import Finding, Pass, Repo

FAULTS_PY = "localai_tpu/testing/faults.py"
CODE_GLOBS = ["localai_tpu/**/*.py", "localai_tpu/*.py"]


def declared_sites(repo: Repo, faults_py: str) -> dict[str, int]:
    """{site: line} from the SITES tuple assignment in faults.py."""
    for node in ast.walk(repo.tree(faults_py)):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "SITES"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return {
                elt.value: elt.lineno
                for elt in node.value.elts
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            }
    return {}


class FaultSitesPass(Pass):
    id = "fault-sites"
    description = (
        "faults.SITES entries without a fire() call site, and fire() calls "
        "with unknown/non-literal site names"
    )

    def __init__(self, faults_py=FAULTS_PY, code_globs=None):
        self.faults_py = faults_py
        self.code_globs = CODE_GLOBS if code_globs is None else code_globs

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        if not repo.exists(self.faults_py):
            return out
        sites = declared_sites(repo, self.faults_py)
        fired: dict[str, list[tuple[str, int]]] = {}
        for path in repo.files(*self.code_globs):
            if path == self.faults_py:
                continue  # the module's own fire() definition/docstring
            for node in ast.walk(repo.tree(path)):
                if not (isinstance(node, ast.Call)
                        and astutil.dotted_name(node.func).split(".")[-1]
                        == "fire"):
                    continue
                # Only faults.fire / fire — skip unrelated .fire() methods
                # by requiring the receiver to be `faults` or a bare import.
                root = astutil.dotted_name(node.func)
                if root not in ("fire", "faults.fire"):
                    continue
                if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    out.append(self.finding(
                        path, node.lineno,
                        "fire(...) with a non-literal site name — the "
                        "fault-site consistency check cannot verify it; "
                        "use a string literal from faults.SITES",
                    ))
                    continue
                name = node.args[0].value
                fired.setdefault(name, []).append((path, node.lineno))
                if name not in sites:
                    out.append(self.finding(
                        path, node.lineno,
                        f"fire({name!r}) names a site missing from "
                        f"faults.SITES — schedules can never target it and "
                        f"parse_env would reject it",
                    ))
        for name, line in sorted(sites.items()):
            if name not in fired:
                out.append(self.finding(
                    self.faults_py, line,
                    f"faults.SITES entry {name!r} has no faults.fire({name!r}) "
                    f"call site in localai_tpu/ — a schedule targeting it "
                    f"silently never fires (the typo'd-site class)",
                ))
        return out
