"""handoff-escape: objects published to another thread too early, or
mutated after being handed off.

Two shapes of the same ownership bug:

1. **Publish before construction completes.** `__init__` (or a method it
   calls) starts a thread — or puts `self` into a queue/registry — and
   THEN keeps assigning attributes. The new thread can observe a
   half-constructed object: exactly the BENCH_r05 class of AttributeError
   (engine loop reading an attr `__init__` had not assigned yet), but
   as a runtime interleaving instead of a missing line. A thread start in
   construction is only flagged when a LATER-assigned attribute is
   actually touched by the spawned root's reachable closure; a `self`
   publish into a queue is flagged on any later assignment (the consumer
   is unknowable).

2. **Mutate after handoff.** `q.put(obj)` transfers ownership — the
   consumer thread processes `obj` concurrently from that line on. A
   producer that keeps writing `obj.attr` after the put races its own
   consumer. (The drain-queue idiom is the blessed direction: the
   CONSUMER writes results onto the entry it got; the producer only
   reads them behind the `host_done` flag.)
"""

from __future__ import annotations

import ast

from .. import astutil
from ..core import Finding, Pass, Repo
from ..summaries import DEFAULT_SUMMARY_GLOBS, MUTATOR_METHODS
from ..threads import threads_for


class HandoffEscapePass(Pass):
    id = "handoff-escape"
    description = (
        "object published to another thread before construction completes, "
        "or mutated by the producer after a queue handoff"
    )
    project_wide = True

    def __init__(self, globs=None):
        self.globs = tuple(DEFAULT_SUMMARY_GLOBS if globs is None else globs)

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        model = threads_for(repo, self.globs)
        idx = model.idx
        graph = model.graph

        # Construction-method fids per class (publish-point scope).
        construction: dict[str, tuple[str, str]] = {}
        for (path, cname) in graph.classes:
            table = graph._methods.get((path, cname), {})
            nodes = {n: graph.funcs[f].node for n, f in table.items()}
            for name in astutil.construction_methods(nodes):
                construction[table[name]] = (path, cname)

        def reach_effect_objs(entry: str) -> set[str]:
            """Attr objs the closure of one entry fid touches."""
            seen: set[str] = set()
            objs: set[str] = set()
            frontier = [entry]
            while frontier:
                fid = frontier.pop()
                if fid in seen:
                    continue
                seen.add(fid)
                s = idx.summaries.get(fid)
                if s is None:
                    continue
                for e in s.effects:
                    objs.add(e.obj)
                for site in s.calls:
                    frontier.extend(site.callees)
            return objs

        def later_self_assigns(fn, me, after_line):
            """[(attr, line)] of self.attr assignments after a line."""
            got = []
            for node in ast.walk(fn):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == me and node.lineno > after_line):
                        got.append((t.attr, node.lineno))
            return sorted(got, key=lambda p: p[1])

        # ---- shape 1a: thread started during construction ---- #
        for site in model.sites:
            owner = construction.get(site.in_summary)
            if owner is None or site.target_fid is None:
                continue
            path, cname = owner
            fd = graph.funcs[site.in_summary]
            me = astutil.self_name(fd.node)
            if me is None:
                continue
            touched = reach_effect_objs(site.target_fid)
            for attr, line in later_self_assigns(fd.node, me, site.line):
                if f"{path}::{cname}.{attr}" in touched:
                    out.append(self.finding(
                        path, line,
                        f"self.{attr} is assigned after the '{site.role}' "
                        f"thread is started at line {site.line}, and that "
                        f"thread's code touches it — the new thread can "
                        f"observe a half-constructed {cname}; start "
                        f"threads at the END of construction",
                    ))
                    break  # one witness per spawn site

        # ---- shape 1b: `self` put into a queue/registry in __init__ ---- #
        for fid, (path, cname) in construction.items():
            fd = graph.funcs[fid]
            me = astutil.self_name(fd.node)
            if me is None:
                continue
            publish_line = None
            for node in ast.walk(fd.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("put", "put_nowait", "append",
                                               "add", "register")
                        and not (isinstance(node.func.value, ast.Name)
                                 and node.func.value.id == me)
                        and any(isinstance(a, ast.Name) and a.id == me
                                for a in node.args)):
                    publish_line = node.lineno
                    break
            if publish_line is None:
                continue
            later = later_self_assigns(fd.node, me, publish_line)
            if later:
                attr, line = later[0]
                out.append(self.finding(
                    path, line,
                    f"self.{attr} is assigned after `self` was published "
                    f"into a queue/registry at line "
                    f"{publish_line} — whoever consumes that handoff can "
                    f"see a half-constructed {cname}; publish last",
                ))

        # ---- shape 2: producer mutates an object after q.put(obj) ---- #
        for fid, fd in graph.funcs.items():
            puts: list[tuple[int, str]] = []
            for node in ast.walk(fd.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("put", "put_nowait")
                        and len(node.args) >= 1
                        and isinstance(node.args[0], ast.Name)):
                    puts.append((node.lineno, node.args[0].id))
            if not puts:
                continue
            for node in ast.walk(fd.node):
                tgt = None
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)):
                            tgt = (t.value.id, node.lineno)
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Attribute)
                      and node.func.attr in MUTATOR_METHODS
                      and isinstance(node.func.value, ast.Attribute)
                      and isinstance(node.func.value.value, ast.Name)):
                    tgt = (node.func.value.value.id, node.lineno)
                if tgt is None:
                    continue
                var, line = tgt
                first_put = next((pl for pl, pv in puts
                                  if pv == var and line > pl), None)
                if first_put is not None:
                    out.append(self.finding(
                        fd.path, line,
                        f"{var} is written at line {line} after "
                        f"being handed off via .put() at line {first_put} "
                        f"— the consumer thread already owns it; finish "
                        f"writes before the handoff (or hand back through "
                        f"a reply queue)",
                    ))
                    break  # one witness per function
        return out
