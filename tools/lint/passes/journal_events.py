"""journal-events: faults.SITES ↔ journal FAULT_EVENTS, both directions.

The flight recorder (localai_tpu/observe/journal.py, ISSUE 11) declares one
journal event type per fault-injection site (`fault_<site>` in
FAULT_EVENTS) so an injected fault is attributable in the postmortem's
journal tail. Nothing ties the two declarations together at runtime — a
site added to `faults.SITES` without its journal event would make that
fault class invisible to the flight recorder, and a `fault_*` event naming
a deleted/renamed site could never be emitted. Same shape as the
`fault-sites` pass, checked both ways:

  * every name in `faults.SITES` has a `fault_<name>` entry in the
    journal's FAULT_EVENTS tuple;
  * every FAULT_EVENTS entry is `fault_<site>` for a site in SITES.
"""

from __future__ import annotations

import ast

from ..core import Finding, Pass, Repo
from .fault_sites import FAULTS_PY, declared_sites

JOURNAL_PY = "localai_tpu/observe/journal.py"


def declared_fault_events(repo: Repo, journal_py: str) -> dict[str, int]:
    """{event: line} from the FAULT_EVENTS tuple in journal.py."""
    for node in ast.walk(repo.tree(journal_py)):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "FAULT_EVENTS"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return {
                elt.value: elt.lineno
                for elt in node.value.elts
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            }
    return {}


class JournalEventsPass(Pass):
    id = "journal-events"
    description = (
        "faults.SITES entries without a journal fault_<site> event type, "
        "and journal fault events naming no fault site"
    )
    # Cross-file invariant: --since must never narrow it away.
    project_wide = True

    def __init__(self, faults_py=FAULTS_PY, journal_py=JOURNAL_PY):
        self.faults_py = faults_py
        self.journal_py = journal_py

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        if not (repo.exists(self.faults_py) and repo.exists(self.journal_py)):
            return out
        sites = declared_sites(repo, self.faults_py)
        events = declared_fault_events(repo, self.journal_py)
        for site, line in sorted(sites.items()):
            if f"fault_{site}" not in events:
                out.append(self.finding(
                    self.faults_py, line,
                    f"faults.SITES entry {site!r} has no journal event type "
                    f"'fault_{site}' in {self.journal_py} FAULT_EVENTS — "
                    f"injected faults at this site would be invisible to "
                    f"the flight recorder",
                ))
        for event, line in sorted(events.items()):
            if not event.startswith("fault_"):
                out.append(self.finding(
                    self.journal_py, line,
                    f"FAULT_EVENTS entry {event!r} does not follow the "
                    f"'fault_<site>' naming — the cross-check cannot map "
                    f"it to a faults.SITES entry",
                ))
                continue
            if event[len("fault_"):] not in sites:
                out.append(self.finding(
                    self.journal_py, line,
                    f"journal FAULT_EVENTS entry {event!r} names no "
                    f"faults.SITES site — the event can never correspond "
                    f"to an injected fault (renamed or deleted site)",
                ))
        return out
