"""lock-discipline: state read under a lock must not be rebound outside it.

Generalized (ISSUE 5) from the single hard-coded Engine/_pending_lock check:
for EVERY class in the engine, manager, and federation-router modules, and
for EVERY lock attribute the class constructs (`self.x = threading.Lock()` /
`RLock()` / `Condition()`), attributes READ inside `with self.x:` somewhere
in the class must never be REBOUND (`self.a = ...` / `self.a += ...`)
outside such a block at runtime — the lock exists because another thread
reads that state, so an unlocked rebind is a torn-read waiting to happen
(Engine.submit() and the loop thread share _pending exactly this way).

Construction (__init__ plus everything it transitively calls on self) is
exempt: no second thread exists yet.
"""

from __future__ import annotations

import ast

from .. import astutil
from ..core import Finding, Pass, Repo

DEFAULT_GLOBS = [
    "localai_tpu/engine/*.py",
    "localai_tpu/server/manager.py",
    "localai_tpu/federation/router.py",
    "localai_tpu/cluster/*.py",
    "localai_tpu/parallel/*.py",
    # Observability layer (ISSUE 11): the journal's staged sidecar and the
    # trace store are written by engine/HTTP threads concurrently.
    "localai_tpu/observe/*.py",
]

_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    """Attributes assigned from threading.Lock()/RLock()/Condition()
    anywhere in the class."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        ctor = astutil.dotted_name(node.value.func)
        if ctor.split(".")[-1] not in _LOCK_CTORS:
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
                out.add(t.attr)
    return out


def check_class_locks(cls: ast.ClassDef, lock_attr: str) -> list[tuple[str, str, int]]:
    """[(attr, method, line)] unlocked rebinds of state read under lock_attr."""
    methods = astutil.methods_of(cls)
    construction = astutil.construction_methods(methods)

    def _is_lock_with(node: ast.With, me: str) -> bool:
        for item in node.items:
            ctx = item.context_expr
            if (isinstance(ctx, ast.Attribute)
                    and isinstance(ctx.value, ast.Name)
                    and ctx.value.id == me and ctx.attr == lock_attr):
                return True
        return False

    reads_locked: set[str] = set()
    rebinds: list[tuple[str, str, int, bool]] = []

    for mname, fn in methods.items():
        me = astutil.self_name(fn)
        if me is None:
            continue
        # Repo convention: a method named *_locked is documented as "caller
        # holds the lock" — its body runs in locked context.
        held_by_caller = mname.endswith("_locked")

        def walk(node: ast.AST, locked: bool, mname=mname, me=me) -> None:
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == me):
                if isinstance(node.ctx, ast.Load) and locked:
                    reads_locked.add(node.attr)
                elif isinstance(node.ctx, ast.Store):
                    rebinds.append((node.attr, mname, node.lineno, locked))
            if isinstance(node, ast.AugAssign):
                t = node.target
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == me):
                    rebinds.append((t.attr, mname, node.lineno, locked))
            child_locked = locked or (
                isinstance(node, ast.With) and _is_lock_with(node, me)
            )
            for child in ast.iter_child_nodes(node):
                walk(child, child_locked)

        walk(fn, held_by_caller)

    # Method/property accesses under the lock are calls, not shared state.
    protected = reads_locked - set(methods) - {lock_attr}
    findings = [
        (attr, mname, line)
        for attr, mname, line, locked in rebinds
        if attr in protected and not locked and mname not in construction
    ]
    return sorted(set(findings), key=lambda f: f[2])


class LockDisciplinePass(Pass):
    id = "lock-discipline"
    description = (
        "state read under a class's lock rebound outside it "
        "(cross-thread torn read)"
    )

    def __init__(self, globs=None):
        self.globs = DEFAULT_GLOBS if globs is None else globs

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for path in repo.files(*self.globs):
            if not repo.in_scope(path):
                continue  # --since incremental mode
            for cls in ast.walk(repo.tree(path)):
                if not isinstance(cls, ast.ClassDef):
                    continue
                for lock_attr in sorted(_lock_attrs(cls)):
                    for attr, mname, line in check_class_locks(cls, lock_attr):
                        out.append(self.finding(
                            path, line,
                            f"self.{attr} rebound in {cls.name}.{mname}() "
                            f"WITHOUT {lock_attr}, but it is read under that "
                            f"lock elsewhere — cross-thread torn read",
                        ))
        return out
