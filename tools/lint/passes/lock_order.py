"""lock-order: cycles in the global lock acquisition-order graph.

PR 6 made the serving path genuinely multi-threaded across module
boundaries: the engine loop, the cluster pump threads, the scheduler's
gauge pulls, the manager watchdog, and the federation health loop all take
locks owned by DIFFERENT classes (`Engine._pending_lock`,
`ClusterScheduler._lock`, `ClusterClient._lock`, `WorkerRegistry._lock`,
`ModelManager._lock`, `LoadedModel._lock`, ...). Two threads taking two of
those locks in opposite orders is a deadlock that no intraprocedural pass
can see — the two halves of the inversion live in different files.

This pass builds the acquisition-order graph interprocedurally
(tools.lint.callgraph + tools.lint.summaries): an edge A→B exists when some
function takes (or may take, transitively through resolved calls) lock B
while holding lock A. The `*_locked` convention is honored — a
single-lock-class method named `*_locked` is assumed to run with its class
lock held. Any cycle in the graph is a potential deadlock and is reported
once per cycle with a witness site per edge.

Additionally: a provably same-instance re-acquisition of a NON-reentrant
threading.Lock (a `self.m()` chain from inside `with self.lock:` into a
method that takes `self.lock` again) is an unconditional self-deadlock and
is reported directly.
"""

from __future__ import annotations

from ..core import Finding, Pass, Repo
from ..summaries import DEFAULT_SUMMARY_GLOBS, summaries_for


def _short(lock: str) -> str:
    """'scheduler.py::ClusterScheduler._lock' for messages."""
    path, _, rest = lock.partition("::")
    return f"{path.rsplit('/', 1)[-1]}::{rest}"


class LockOrderPass(Pass):
    id = "lock-order"
    description = (
        "cycle in the cross-module lock acquisition-order graph "
        "(potential deadlock between serving threads)"
    )
    project_wide = True  # the graph spans files; --since cannot narrow it

    def __init__(self, globs=None):
        # Default scope rides the shared union SummaryIndex (one build
        # serves all four interprocedural passes); custom globs (fixtures)
        # build their own small index.
        self.globs = tuple(DEFAULT_SUMMARY_GLOBS if globs is None else globs)

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        idx = summaries_for(repo, self.globs)
        may = idx.may_acquire()

        # edge (held, acquired) -> witness (path, line, context)
        edges: dict[tuple[str, str], tuple[str, int, str]] = {}

        def add_edge(a: str, b: str, path: str, line: int, ctx: str) -> None:
            edges.setdefault((a, b), (path, line, ctx))

        for fid, s in idx.summaries.items():
            where = f"{s.cls + '.' if s.cls else ''}{s.name}()"
            for acq in s.acquisitions:
                for h in acq.held:
                    if h == acq.lock:
                        if idx.lock_kinds.get(h) == "Lock":
                            out.append(self.finding(
                                s.path, acq.line,
                                f"{_short(h)} re-acquired in {where} while "
                                f"already held — threading.Lock is not "
                                f"reentrant; this path deadlocks itself",
                            ))
                        continue
                    add_edge(h, acq.lock, s.path, acq.line,
                             f"{where} takes {_short(acq.lock)}")
            for site in s.calls:
                if not site.held:
                    continue
                for callee in site.callees:
                    for m in may.get(callee, ()):
                        cs = idx.summaries.get(callee)
                        cname = (f"{cs.cls + '.' if cs and cs.cls else ''}"
                                 f"{cs.name if cs else callee}")
                        for h in site.held:
                            if h == m:
                                # Same-id, same-instance only when the call
                                # chain is provably `self.` — cross-instance
                                # same-slot locks are different objects.
                                if (site.self_call
                                        and idx.lock_kinds.get(h) == "Lock"
                                        and m in {a.lock for a in
                                                  (cs.acquisitions if cs else ())}):
                                    out.append(self.finding(
                                        s.path, site.line,
                                        f"{where} holds {_short(h)} and calls "
                                        f"{cname}(), which takes the same "
                                        f"non-reentrant lock — self-deadlock "
                                        f"(use the *_locked convention)",
                                    ))
                                continue
                            add_edge(h, m, s.path, site.line,
                                     f"{where} -> {cname}() "
                                     f"takes {_short(m)}")

        # Cycle detection over the lock graph (DFS; each cycle reported at
        # its first witness edge).
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())

        seen_cycles: set[frozenset] = set()

        def find_cycle_from(start: str):
            stack = [(start, [start])]
            visited = set()
            while stack:
                node, trail = stack.pop()
                for nxt in graph.get(node, ()):
                    if nxt == start:
                        return trail + [start]
                    if nxt not in visited:
                        visited.add(nxt)
                        stack.append((nxt, trail + [nxt]))
            return None

        for start in sorted(graph):
            cycle = find_cycle_from(start)
            if not cycle:
                continue
            key = frozenset(cycle)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            legs = []
            wpath, wline = None, 0
            for a, b in zip(cycle, cycle[1:]):
                path, line, ctx = edges[(a, b)]
                if wpath is None:
                    wpath, wline = path, line
                legs.append(f"{_short(a)} -> {_short(b)} ({ctx} at "
                            f"{path}:{line})")
            out.append(self.finding(
                wpath, wline,
                "lock-order cycle — two threads taking these locks in "
                "opposite orders deadlock: " + "; ".join(legs),
            ))
        return out
