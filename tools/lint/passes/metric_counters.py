"""metric-counters: every `self.m_*` counter a class's `metrics()` method
reads must be UNCONDITIONALLY initialized during construction.

The general attr-init pass already catches never-assigned reads; this
stricter companion exists because metric counters are the repeat offender
(the BENCH_r05 rc=124 class) — they get added at a dispatch site (so
attr-init sees an assignment *somewhere*), read in metrics(), and the
__init__ line is what gets forgotten: the first /metrics scrape of a fresh
engine then raises AttributeError.

Generalized from the hard-coded Engine check: applies to every class under
localai_tpu/ that defines a `metrics()` method.
"""

from __future__ import annotations

import ast

from .. import astutil
from ..core import Finding, Pass, Repo

DEFAULT_GLOBS = ["localai_tpu/**/*.py", "localai_tpu/*.py"]


def uninitialized_counters(cls, module_classes=None):
    """[(attr, line)] of m_* counters metrics() reads but construction never
    assigns. Function-level API kept for the check_engine_attrs shim."""
    methods = astutil.methods_of(cls)
    if "metrics" not in methods:
        return []
    init_assigned: set[str] = set()
    for name in astutil.construction_methods(methods):
        init_assigned |= astutil.attr_stores(methods[name])
    if module_classes:
        # super().__init__ runs same-module base constructors.
        for base in cls.bases:
            bname = (base.id if isinstance(base, ast.Name)
                     else getattr(base, "attr", ""))
            bcls = module_classes.get(bname)
            if bcls is not None and bcls is not cls:
                init_assigned |= astutil.construction_assigned(
                    bcls, module_classes
                )
    exempt = astutil.hasattr_probes(cls)
    return sorted(
        (attr, line)
        for attr, line in astutil.attr_reads(methods["metrics"]).items()
        if attr.startswith("m_")
        and attr not in init_assigned
        and attr not in exempt
    )


class MetricCountersPass(Pass):
    id = "metric-counters"
    description = (
        "m_* counter read in metrics() but not initialized in __init__ "
        "(fresh-instance scrape AttributeError)"
    )

    def __init__(self, globs=None):
        self.globs = DEFAULT_GLOBS if globs is None else globs

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for path in repo.files(*self.globs):
            if not repo.in_scope(path):
                continue  # --since incremental mode
            tree = repo.tree(path)
            module_classes = repo.classes(path)
            for cls in ast.walk(tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                for attr, line in uninitialized_counters(cls, module_classes):
                    out.append(self.finding(
                        path, line,
                        f"metric counter self.{attr} read in "
                        f"{cls.name}.metrics() but never initialized in "
                        f"__init__ — the scrape would AttributeError on "
                        f"a fresh instance",
                    ))
        return out
