"""net-call-deadline: every outbound network call states an explicit timeout.

The cluster's remote-call surface (role probes, gauge scrapes, span fetches,
RemoteEngine proxying) runs on threads the caller is waiting on: an
`urllib.request.urlopen(...)` with no `timeout=` inherits the global socket
default — None, i.e. block forever — and one wedged peer pins the calling
thread for the life of the process. ISSUE 19's netretry/breaker layer only
works if the underlying call actually returns; a missing timeout turns every
retry policy into a single infinite attempt.

Flagged in production code (localai_tpu/):

  * `urllib.request.urlopen(...)` / `request.urlopen(...)` / bare
    `urlopen(...)` calls without an explicit `timeout=` keyword;
  * `socket.create_connection(...)` without a timeout (positional arg 2 or
    `timeout=` keyword) and `socket.setdefaulttimeout(...)` (process-global
    mutation — per-call deadlines are the contract).

A literal `timeout=None` is also flagged: it states the default rather than
a deadline. Tests are exempt (they may probe hang behaviour on purpose).
"""

from __future__ import annotations

import ast

from .. import astutil
from ..core import Finding, Pass, Repo

CODE_GLOBS = ["localai_tpu/**/*.py", "localai_tpu/*.py"]

URLOPEN_NAMES = ("urlopen", "request.urlopen", "urllib.request.urlopen")
CREATE_CONN_NAMES = ("create_connection", "socket.create_connection")
SETDEFAULT_NAMES = ("setdefaulttimeout", "socket.setdefaulttimeout")


def _timeout_kw(node: ast.Call):
    for kw in node.keywords:
        if kw.arg == "timeout":
            return kw
    return None


class NetCallDeadlinePass(Pass):
    id = "net-call-deadline"
    description = (
        "outbound network calls (urlopen / socket connect) without an "
        "explicit timeout — a wedged peer pins the calling thread forever"
    )

    def __init__(self, code_globs=None):
        self.code_globs = CODE_GLOBS if code_globs is None else code_globs

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for path in repo.files(*self.code_globs):
            for node in ast.walk(repo.tree(path)):
                if not isinstance(node, ast.Call):
                    continue
                name = astutil.dotted_name(node.func)
                if name in URLOPEN_NAMES:
                    kw = _timeout_kw(node)
                    if kw is None:
                        # A **kwargs splat may carry the timeout — flag only
                        # calls with no splat (a splat defeats static proof
                        # but is not used on this surface today).
                        if any(k.arg is None for k in node.keywords):
                            continue
                        out.append(self.finding(
                            path, node.lineno,
                            "urlopen(...) without an explicit timeout= — "
                            "inherits the global socket default (block "
                            "forever); pass the request's deadline",
                        ))
                    elif (isinstance(kw.value, ast.Constant)
                            and kw.value.value is None):
                        out.append(self.finding(
                            path, node.lineno,
                            "urlopen(..., timeout=None) states the "
                            "block-forever default — pass a finite deadline",
                        ))
                elif name in CREATE_CONN_NAMES:
                    if len(node.args) < 2 and _timeout_kw(node) is None:
                        out.append(self.finding(
                            path, node.lineno,
                            "socket.create_connection(...) without a "
                            "timeout — pass the call's deadline",
                        ))
                elif name in SETDEFAULT_NAMES:
                    out.append(self.finding(
                        path, node.lineno,
                        "socket.setdefaulttimeout(...) mutates process-"
                        "global state — use per-call timeout= instead",
                    ))
        return out
