"""page-refcount: allocator discipline for the paged-KV pool and host tier.

The PR 3 allocator bugs (double releases, a stale table overwritten into a
permanent pool leak, the 107k-preemption livelock) all reduced to page
bookkeeping happening OUTSIDE the allocator primitives, where no invariant
walk could see it. Rules, per class (default Engine):

1. PRIMITIVES ONLY: `self._free_pages` and `self._page_refs` may be mutated
   only inside the allocator primitives (`_pages_claim` / `_pages_addref` /
   `_pages_release`, plus `_pages_alloc` composing them) and construction.
   Any other method popping the free list or touching refcounts is
   untracked accounting.

2. CHECKED ALLOCATION: every `_pages_alloc(...)` / `_pages_claim(...)` call
   outside the primitives must handle the None return (pool full) — the
   result must be None-compared in the same method (or the call itself sit
   in an `if` test). An unchecked alloc turns pool backpressure into a
   loop-killing TypeError three lines later.

3. RELEASE ON ERROR EDGES: every allocation (`_pages_alloc` /
   `_pages_claim` / `_pages_addref` outside the primitives) must resolve on
   EVERY exception-edge CFG path — released, freed, or ownership
   transferred into a tracked table / prefix container / requeue. Since
   ISSUE 20 this is the kv-pages protocol of the resource registry
   (tools.lint.resources) run in leak mode over the exception-edge CFG,
   replacing the old lexical "a try that mentions _pages_free exists
   somewhere in the body" check: the release must actually lie on the
   leaking path, not merely in the same method.

4. NO ESCAPED PAGE IDS: page ids live only in the tracked tables
   (`_slot_pages`, `h_ptable`, the refcount/free structures) or flow
   through the allocator's return value. Storing a page list into any other
   `self.<attr>` hides references from the invariant walk.
"""

from __future__ import annotations

import ast
import os

from .. import astutil
from ..core import Finding, Pass, Repo
from ..resources import KV_PAGES, analyze_protocol, releasing_methods
from ..summaries import summaries_for

DEFAULT_TARGETS = [("localai_tpu/engine/engine.py", "Engine")]

PRIMITIVES = {"_pages_alloc", "_pages_release", "_pages_claim",
              "_pages_addref"}
ALLOC_CALLS = {"_pages_alloc", "_pages_claim"}
POOL_ATTRS = {"_free_pages", "_page_refs"}
TRACKED_TABLES = {"_slot_pages", "h_ptable", "_free_pages", "_page_refs"}
# Containers whose entries own page references with a release path of
# their own (_prefix_drop): inserting pages here transfers ownership.
TRACKED_CONTAINERS = {"_prefix_entries", "_prefix_host"}
_MUTATING_CALLS = {"pop", "append", "appendleft", "extend", "clear",
                   "insert", "remove"}


def _names_in(node: ast.AST) -> set[str]:
    return {
        astutil.dotted_name(sub).split(".")[-1]
        for sub in ast.walk(node)
        if isinstance(sub, (ast.Attribute, ast.Name))
    }


class PageRefcountPass(Pass):
    id = "page-refcount"
    description = (
        "page-pool booking outside the allocator primitives / unchecked "
        "alloc / alloc without a release edge / escaped page ids"
    )

    def __init__(self, targets=None):
        self.targets = DEFAULT_TARGETS if targets is None else targets

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for path, class_name in self.targets:
            if not repo.exists(path) or not repo.in_scope(path):
                continue
            cls = repo.find_class(path, class_name)
            if cls is None:
                continue
            methods = astutil.methods_of(cls)
            construction = astutil.construction_methods(methods)
            for mname, fn in methods.items():
                me = astutil.self_name(fn)
                if me is None:
                    continue
                in_primitive = mname in PRIMITIVES or mname in construction

                def self_attr(node) -> str:
                    if (isinstance(node, ast.Attribute)
                            and isinstance(node.value, ast.Name)
                            and node.value.id == me):
                        return node.attr
                    return ""

                alloc_calls: list[ast.Call] = []
                none_checked: set[str] = set()  # local names None-compared
                calls_in_if_test: set[int] = set()

                for node in ast.walk(fn):
                    # R1: pool-structure mutation outside primitives.
                    if not in_primitive:
                        if (isinstance(node, ast.Call)
                                and isinstance(node.func, ast.Attribute)
                                and node.func.attr in _MUTATING_CALLS
                                and self_attr(node.func.value) in POOL_ATTRS):
                            out.append(self.finding(
                                path, node.lineno,
                                f"{class_name}.{mname}() mutates "
                                f"self.{self_attr(node.func.value)} directly — "
                                f"page-pool booking belongs in the allocator "
                                f"primitives ({sorted(PRIMITIVES)}) where the "
                                f"invariant walk can see it",
                            ))
                        if isinstance(node, (ast.Assign, ast.AugAssign)):
                            targets = (node.targets
                                       if isinstance(node, ast.Assign)
                                       else [node.target])
                            for t in targets:
                                for tt in ast.walk(t):
                                    if (isinstance(tt, ast.Subscript)
                                            and self_attr(tt.value) in POOL_ATTRS):
                                        out.append(self.finding(
                                            path, node.lineno,
                                            f"{class_name}.{mname}() writes "
                                            f"self.{self_attr(tt.value)}[...] — "
                                            f"refcount mutation outside the "
                                            f"allocator primitives",
                                        ))

                    # Collect allocation calls + None checks (R2/R3).
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr in ALLOC_CALLS
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id == me):
                        alloc_calls.append(node)
                    if isinstance(node, ast.Compare):
                        ops_none = any(
                            isinstance(c, ast.Constant) and c.value is None
                            for c in node.comparators
                        )
                        if ops_none:
                            for sub in ast.walk(node.left):
                                if isinstance(sub, ast.Name):
                                    none_checked.add(sub.id)
                            for sub in ast.walk(node):
                                if isinstance(sub, ast.Call):
                                    calls_in_if_test.add(id(sub))
                    if isinstance(node, ast.Assign):
                        # R4: page lists escaping into untracked attributes.
                        rhs_names = _names_in(node.value)
                        if ("_pages_alloc" in rhs_names
                                or "_slot_pages" in rhs_names
                                or "_free_pages" in rhs_names):
                            for t in node.targets:
                                a = self_attr(t)
                                sub_a = (self_attr(t.value)
                                         if isinstance(t, ast.Subscript) else "")
                                if ((a and a not in TRACKED_TABLES)
                                        or (sub_a and sub_a not in TRACKED_TABLES
                                            and sub_a != "slots")):
                                    if in_primitive:
                                        continue
                                    out.append(self.finding(
                                        path, node.lineno,
                                        f"{class_name}.{mname}() stores page "
                                        f"ids into self.{a or sub_a} — outside "
                                        f"the tracked tables "
                                        f"({sorted(TRACKED_TABLES)}); the "
                                        f"invariant walk cannot see this "
                                        f"reference",
                                    ))
                if in_primitive or not alloc_calls:
                    continue

                # R2: every alloc result must be None-checked.
                assigned_to: dict[int, str] = {}
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)
                            and node.value in alloc_calls):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                assigned_to[id(node.value)] = t.id
                for call in alloc_calls:
                    name = assigned_to.get(id(call))
                    checked = (
                        (name is not None and name in none_checked)
                        or id(call) in calls_in_if_test
                    )
                    if not checked:
                        out.append(self.finding(
                            path, call.lineno,
                            f"{class_name}.{mname}() calls "
                            f"{call.func.attr}() without handling the None "
                            f"(pool-full) return — backpressure becomes a "
                            f"loop-killing TypeError",
                        ))

            # R3: every allocation resolves on every exception-edge CFG
            # path — the kv-pages protocol in leak mode, with the class's
            # transitively-releasing helpers (e.g. _resume_discard) as
            # blanket resolves.
            rel = os.path.relpath(repo.abspath(path),
                                  repo.root).replace(os.sep, "/")
            index = summaries_for(repo, (rel,))
            extra = tuple(releasing_methods(methods))
            for fid, fd in index.graph.funcs.items():
                if fd.path != rel or fd.cls != class_name:
                    continue
                if fd.name in construction:
                    continue  # no consumer can observe a half-built pool
                for iss in analyze_protocol(repo, index, fd, (KV_PAGES,),
                                            mode="leak",
                                            extra_blanket_resolves=extra):
                    if iss.kind != "leak":
                        continue
                    exit_desc = ("the function's exception exit"
                                 if iss.exit_kind == "raise-exit"
                                 else "a return")
                    out.append(self.finding(
                        path, iss.line,
                        f"{class_name}.{fd.name}() allocates pages here but "
                        f"{exit_desc} (via line {iss.exit_line}) is "
                        f"reachable without releasing or installing them — "
                        f"the pages leak from the pool until restart",
                        witness=iss.witness,
                    ))
        return out
