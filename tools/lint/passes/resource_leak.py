"""resource-leak: an acquired resource must resolve on EVERY exit path.

The PR 19 incident class, generalized (ISSUE 20): `call_with_retry`
admitted a circuit-breaker half-open probe, then exited through the
HTTPError edge without `record_success`/`record_failure`/`release_probe` —
the breaker wedged half-open and refused every future call to that
replica. The same shape: a `pick(reserve=True)` inflight reservation
abandoned before `end_stream`, an adapter pin dropped on an early return,
a manually-acquired lock left held on a raise, a stream handle lost on a
typed-error edge.

The rule is protocol-generic: for every acquisition declared in
tools.lint.resources, every path of the exception-edge CFG (tools.lint.cfg
— `raise`, handler, finally, and may-raise call edges included) from the
acquire site to EXIT or RAISE_EXIT must contain a resolve primitive or an
ownership transfer (return of the handle, store into the protocol's
declared owner container). The first leaking path is reported with a
line-numbered witness trace (Finding.witness; `--json` carries it stably).

kv pages are checked by the page-refcount pass (same registry declaration,
different finding vocabulary); this pass covers the other five protocols.
"""

from __future__ import annotations

from ..core import Finding, Pass, Repo
from ..resources import (ADAPTER_PIN, BREAKER_PROBE, LOCK_MANUAL, NET_HANDLE,
                         SCHED_INFLIGHT, analyze_protocol)
from ..summaries import DEFAULT_SUMMARY_GLOBS, summaries_for

DEFAULT_PROTOCOLS = (BREAKER_PROBE, SCHED_INFLIGHT, ADAPTER_PIN, LOCK_MANUAL,
                     NET_HANDLE)

_EXIT_DESC = {
    "exit": "a normal exit",
    "raise-exit": "the function's exception exit",
}


class ResourceLeakPass(Pass):
    id = "resource-leak"
    description = (
        "acquisition (probe slot / inflight reservation / adapter pin / "
        "lock / net handle) with a CFG exit path that never resolves it"
    )

    def __init__(self, globs=None, protocols=None):
        self.globs = tuple(globs) if globs else DEFAULT_SUMMARY_GLOBS
        self.protocols = tuple(protocols) if protocols else DEFAULT_PROTOCOLS

    def run(self, repo: Repo) -> list[Finding]:
        index = summaries_for(repo, self.globs)
        acquire_names = sorted({s.call for p in self.protocols
                                for s in p.acquires})
        hot_path: dict[str, bool] = {}
        out: list[Finding] = []
        for fid, fd in index.graph.funcs.items():
            if not repo.in_scope(fd.path):
                continue
            if fd.path not in hot_path:
                src = repo.source(fd.path)
                hot_path[fd.path] = any(n in src for n in acquire_names)
            if not hot_path[fd.path]:
                continue
            for iss in analyze_protocol(repo, index, fd, self.protocols,
                                        mode="leak"):
                proto = iss.protocol
                where = _EXIT_DESC.get(iss.exit_kind, iss.exit_kind)
                owner = f"{fd.cls}.{fd.name}" if fd.cls else fd.name
                out.append(self.finding(
                    fd.path, iss.line,
                    f"{owner}() acquires a {proto.what} here but {where} "
                    f"(via line {iss.exit_line}) is reachable without "
                    f"resolving it — the {proto.pid} protocol leaks on "
                    f"that path (the PR 19 probe-slot incident class); "
                    f"resolve with one of "
                    f"{sorted(proto.resolves + proto.blanket_resolves)} "
                    f"or transfer ownership",
                    witness=iss.witness,
                ))
        return out
