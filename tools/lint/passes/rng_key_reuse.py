"""rng-key-reuse: one jax.random key feeding two consumers.

JAX PRNG keys are values, not stateful generators: drawing from the same
key twice produces IDENTICAL (or correlated) randomness. The engine's whole
sampling story is an RNG CHAIN built on this invariant — per-slot keys
split once per drawn token, the swap/recompute resume path advances the
saved key one split so re-admitted slots match the uncontended run
byte-for-byte, and the spec-decode accept loop splits per verify step. One
code path that consumes a key twice (two samplers, or sampling from a key
after splitting it) silently correlates "independent" draws — the kind of
bug that passes every shape check and corrupts sampled output only.

Rule, per function scope: a key-typed binding (assigned from
jax.random.key/PRNGKey/split/fold_in/wrap_key_data, or a key-named
parameter) may be consumed at most ONCE per binding generation. Consumers:
jax.random samplers, jax.random.split (using a parent after splitting it),
jax.vmap-wrapped forms of either, and project helpers whose summary says
they consume their key parameter (tools.lint.summaries — the
interprocedural part). `fold_in(key, i)` does NOT consume: deriving
per-step keys from one base via fold_in is the blessed pattern.
Control flow: rebinds start a new generation, if/else branches merge
conservatively, and loop bodies are walked twice so "same key drawn every
iteration" surfaces.
"""

from __future__ import annotations

import ast

from .. import astutil
from ..core import Finding, Pass, Repo
from ..flow import FlowState, LinearFlow
from ..summaries import DEFAULT_SUMMARY_GLOBS, KEY_CONSUMERS, summaries_for

DEFAULT_GLOBS = (
    "localai_tpu/engine/*.py",
    "localai_tpu/models/*.py",
    "localai_tpu/ops/*.py",
)

# Calls whose RESULT is a key (or batch/array of keys).
KEY_PRODUCERS = {"key", "PRNGKey", "split", "fold_in", "wrap_key_data"}
KEY_PARAM_NAMES = {"key", "rng", "rngs", "prng_key", "base_key"}


def _jax_random_fn(name: str) -> str:
    """'categorical' for 'jax.random.categorical', '' when not jax.random."""
    if name.startswith("jax.random."):
        return name.split(".")[-1]
    return ""


def _vmap_inner(call: ast.Call):
    """For `jax.vmap(f)(args)` / `jax.vmap(f, ...)(args)`: the wrapped f
    node, else None."""
    if (isinstance(call.func, ast.Call)
            and astutil.dotted_name(call.func.func) in ("jax.vmap", "vmap")
            and call.func.args):
        return call.func.args[0]
    return None


def _names_outside_calls(node: ast.AST):
    """Name ids in an argument expression, NOT descending into nested
    calls — `normal(fold_in(key, i))` consumes fold_in's fresh result, not
    `key` itself (the nested call was already evaluated on its own)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, ast.Call):
            continue
        if isinstance(cur, ast.Name):
            yield cur.id
        stack.extend(ast.iter_child_nodes(cur))


def _lambda_consumes_param(lam: ast.Lambda) -> bool:
    """Does the lambda body consume any of its own params as a key?"""
    params = {a.arg for a in lam.args.args}
    for node in ast.walk(lam.body):
        if isinstance(node, ast.Call):
            fn = _jax_random_fn(astutil.dotted_name(node.func))
            if fn in KEY_CONSUMERS:
                for a in node.args:
                    for sub in ast.walk(a):
                        if isinstance(sub, ast.Name) and sub.id in params:
                            return True
    return False


class _KeyFlow(LinearFlow):
    def __init__(self, pass_globs, repo, path, fn):
        super().__init__()
        self.repo = repo
        self.path = path
        self.fn = fn
        self.idx = summaries_for(repo, pass_globs)
        self.graph = self.idx.graph
        self.fd = self.graph._by_node.get(id(fn))
        self.ltypes = (self.graph.local_types(path, fn)
                       if self.fd is not None else {})

    # -------- key-ness -------- #

    def _expr_is_key(self, node: ast.AST, st: FlowState) -> bool:
        """Does this RHS produce a key-typed value? STRUCTURAL, not
        contains-based: a producer call (key/split/fold_in/..., plain or
        vmap-wrapped), a tracked name, or a subscript/tuple thereof. A
        SAMPLER call is data even when a key appears in its args — marking
        `u = uniform(key)` as a key would flag every later use of u."""
        if isinstance(node, ast.Call):
            fn = _jax_random_fn(astutil.dotted_name(node.func))
            if fn in KEY_PRODUCERS:
                return True
            if fn in KEY_CONSUMERS:
                return False
            inner = _vmap_inner(node)
            if inner is not None:
                nm = _jax_random_fn(astutil.dotted_name(inner))
                if nm in KEY_PRODUCERS:
                    return True
                if nm in KEY_CONSUMERS:
                    return False
                if isinstance(inner, ast.Lambda):
                    prods = [
                        _jax_random_fn(astutil.dotted_name(c.func))
                        for c in ast.walk(inner.body)
                        if isinstance(c, ast.Call)
                    ]
                    if any(p in KEY_PRODUCERS for p in prods):
                        return True
            return False
        if isinstance(node, ast.Name):
            return node.id in st.tracked
        if isinstance(node, ast.Subscript):
            return self._expr_is_key(node.value, st)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._expr_is_key(e, st) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self._expr_is_key(node.value, st)
        if isinstance(node, ast.IfExp):
            return (self._expr_is_key(node.body, st)
                    or self._expr_is_key(node.orelse, st))
        return False

    # -------- consumption -------- #

    def _call_consumes(self, call: ast.Call) -> bool:
        name = astutil.dotted_name(call.func)
        if _jax_random_fn(name) in KEY_CONSUMERS:
            return True
        inner = _vmap_inner(call)
        if inner is not None:
            if _jax_random_fn(astutil.dotted_name(inner)) in KEY_CONSUMERS:
                return True
            if isinstance(inner, ast.Lambda) and _lambda_consumes_param(inner):
                return True
        # Project helper whose summary consumes a key param.
        if self.fd is not None:
            for fid in self.graph.resolve(self.fd, call, self.ltypes):
                s = self.idx.summaries.get(fid)
                if s and s.key_params_consumed:
                    return True
        return False

    def handle_expr(self, node: ast.AST, st: FlowState) -> None:
        # Evaluate nested calls innermost-first so `split(key)` inside a
        # larger expression registers before the enclosing call.
        if isinstance(node, ast.Call):
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                self.handle_expr(a, st)
            if isinstance(node.func, ast.Call):
                self.handle_expr(node.func, st)
            if self._call_consumes(node):
                for a in list(node.args) + [kw.value for kw in node.keywords]:
                    for name in _names_outside_calls(a):
                        if name in st.tracked:
                            first = st.consume(name, node.lineno)
                            if first is not None:
                                self.record(node.lineno, first, name)
            return
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return  # separate scope
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.handle_expr(child, st)

    def handle_assign(self, stmt, st: FlowState) -> None:
        value = getattr(stmt, "value", None)
        if value is not None:
            self.handle_expr(value, st)
        is_key = value is not None and self._expr_is_key(value, st)
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    st.rebind(sub.id, still_tracked=is_key)

    # -------- entry -------- #

    def run(self, st: FlowState) -> None:
        args = self.fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            if a.arg in KEY_PARAM_NAMES or a.arg.endswith("_key"):
                st.track(a.arg)
        self.exec_block(self.fn.body, st)


def _scopes(tree: ast.Module):
    """Every function scope in the module (methods and nested defs
    included) — each analyzed independently, matching trace-safety's
    scope discipline."""
    for node in ast.walk(tree):
        if isinstance(node, astutil.FunctionNode):
            yield node


class RngKeyReusePass(Pass):
    id = "rng-key-reuse"
    description = (
        "jax.random key consumed twice without an interleaving "
        "split/fold_in (correlated 'independent' draws)"
    )

    def __init__(self, globs=None):
        self.globs = tuple(DEFAULT_GLOBS if globs is None else globs)
        # Helper summaries come from the shared union index on default scope.
        self.summary_globs = (DEFAULT_SUMMARY_GLOBS if globs is None
                              else self.globs)

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for path in repo.files(*self.globs):
            if not repo.in_scope(path):
                continue
            for fn in _scopes(repo.tree(path)):
                walker = _KeyFlow(self.summary_globs, repo, path, fn)
                walker.run(FlowState())
                for line, first, name in sorted(walker.hits.values()):
                    out.append(self.finding(
                        path, line,
                        f"jax.random key {name!r} consumed again (first "
                        f"consumed at line {first}) with no interleaving "
                        f"split/fold_in rebind — the two consumers draw "
                        f"CORRELATED randomness; split the key and pass "
                        f"the subkeys",
                    ))
        return out
