"""sharding-consistency: the mesh-sharded engine's cross-file name contracts.

Tensor-parallel serving (PR 7) is correct only while four files agree on
names that Python never checks (PAPERS.md "Scalable Training of Language
Models using JAX pjit and TPUv4" — spec/tree mismatch is the canonical
sharded-training failure, and it fails SILENTLY: a missing spec replicates
the weight, a stale spec KeyErrors at load, a typo'd mesh axis shards over
nothing):

  C1  parallel/sharding.py `*_specs` names  <->  models/llama.py param-tree
      names. Every PartitionSpec name must exist in the tree built by
      init_params/_init_attn_layers and vice versa — both directions,
      compared as NAME SETS (flag conditions differ per-arch; a name that
      exists on NEITHER side of any arch is drift).

  C2  every mesh-axis string — in PartitionSpec(...) literals and in
      collective axis arguments (psum/pmax/ppermute/all_gather/axis_index/
      ...) — must be declared in parallel/mesh.py AXES. A typo'd axis
      compiles fine and shards over a 1-sized ghost axis.

  C3  collectives run ONLY inside declared boundary functions: a module
      that issues jax.lax collectives must declare them in a module-level
      `COLLECTIVE_BOUNDARY = ("fn", ...)` tuple (ops/attention.py's
      sp-partials, parallel/ring.py's ring rotation). A collective outside
      a declared boundary is an undeclared ICI dependency on the per-token
      path — exactly what the head-sharded kernel work (ISSUE 7) exists to
      prevent; a declared boundary with no collective is a stale
      declaration and is also flagged.
"""

from __future__ import annotations

import ast

from .. import astutil
from ..core import Finding, Pass, Repo

SHARDING_PY = "localai_tpu/parallel/sharding.py"
LLAMA_PY = "localai_tpu/models/llama.py"
MESH_PY = "localai_tpu/parallel/mesh.py"

COLLECTIVE_GLOBS = [
    "localai_tpu/ops/*.py",
    "localai_tpu/parallel/*.py",
    "localai_tpu/models/*.py",
    "localai_tpu/engine/*.py",
    "localai_tpu/train/*.py",
]

# Data-moving collectives that MUST live inside a declared boundary.
COLLECTIVES = {"psum", "pmax", "pmin", "ppermute", "all_gather",
               "all_to_all", "psum_scatter", "pmean"}
# Axis-consuming calls checked against AXES (first positional axis arg
# after the value operand, or the axis_name/axis keyword).
AXIS_CALLS = COLLECTIVES | {"axis_index", "axis_size"}

TREE_FNS = ("init_params", "_init_attn_layers")


def _is_spec_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and astutil.dotted_name(node.func).split(".")[-1]
            in ("P", "PartitionSpec"))


def _collect_str_keys(fn) -> dict[str, int]:
    """String keys assigned in a function: dict literals and
    `X["key"] = ...` subscript stores."""
    out: dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.setdefault(k.value, k.lineno)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and isinstance(t.slice.value, str)):
                    out.setdefault(t.slice.value, t.lineno)
    return out


class ShardingConsistencyPass(Pass):
    id = "sharding-consistency"
    description = (
        "param_specs/param-tree name drift, undeclared mesh axes, and "
        "collectives outside declared boundary functions"
    )
    project_wide = True  # the contract spans four files by construction

    def __init__(self, sharding_py=SHARDING_PY, llama_py=LLAMA_PY,
                 mesh_py=MESH_PY, collective_globs=None, tree_fns=TREE_FNS):
        self.sharding_py = sharding_py
        self.llama_py = llama_py
        self.mesh_py = mesh_py
        self.collective_globs = (COLLECTIVE_GLOBS if collective_globs is None
                                 else collective_globs)
        self.tree_fns = tree_fns

    # ---------------- C1: specs <-> tree ---------------- #

    def _spec_names(self, repo: Repo) -> dict[str, int]:
        out: dict[str, int] = {}
        for node in repo.tree(self.sharding_py).body:
            if not isinstance(node, astutil.FunctionNode):
                continue
            if not (node.name.endswith("_specs") or node.name == "param_specs"):
                continue
            out.update(_collect_str_keys(node))
        return out

    def _tree_names(self, repo: Repo) -> dict[str, int]:
        out: dict[str, int] = {}
        for node in repo.tree(self.llama_py).body:
            if (isinstance(node, astutil.FunctionNode)
                    and node.name in self.tree_fns):
                out.update(_collect_str_keys(node))
        return out

    def _check_names(self, repo: Repo, out: list[Finding]) -> None:
        if not (repo.exists(self.sharding_py) and repo.exists(self.llama_py)):
            return
        specs = self._spec_names(repo)
        tree = self._tree_names(repo)
        if not specs or not tree:
            return
        for name, line in sorted(specs.items()):
            if name not in tree:
                out.append(self.finding(
                    self.sharding_py, line,
                    f"param spec {name!r} has no matching name in the "
                    f"param tree ({self.llama_py} {'/'.join(self.tree_fns)})"
                    f" — a stale spec KeyErrors placement or shards a "
                    f"tensor that no longer exists",
                ))
        for name, line in sorted(tree.items()):
            if name not in specs:
                out.append(self.finding(
                    self.llama_py, line,
                    f"param tree name {name!r} has no PartitionSpec in "
                    f"{self.sharding_py} — the weight would materialize "
                    f"REPLICATED on every chip (or break the spec/param "
                    f"tree_map) under tp>1",
                ))

    # ---------------- C2 + C3: axes and boundaries ---------------- #

    def _declared_axes(self, repo: Repo) -> set[str]:
        if not repo.exists(self.mesh_py):
            return set()
        for node in repo.tree(self.mesh_py).body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "AXES"
                            for t in node.targets)
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                return {
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
        return set()

    @staticmethod
    def _boundary_decl(tree: ast.Module):
        """(names, line) of the module-level COLLECTIVE_BOUNDARY tuple, or
        (None, 0) when the module declares none."""
        for node in tree.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "COLLECTIVE_BOUNDARY"
                            for t in node.targets)
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                return ({
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }, node.lineno)
        return None, 0

    @staticmethod
    def _axis_arg(call: ast.Call):
        """The axis-name argument of a collective/axis call: axis_index(ax)
        takes it first, value-collectives take it second; axis_name= /
        axis= keywords win."""
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axis"):
                return kw.value
        name = astutil.dotted_name(call.func).split(".")[-1]
        idx = 0 if name in ("axis_index", "axis_size") else 1
        if len(call.args) > idx:
            return call.args[idx]
        return None

    def _check_collectives(self, repo: Repo, axes: set[str],
                           out: list[Finding]) -> None:
        files = list(dict.fromkeys(
            repo.files(*self.collective_globs) + [self.sharding_py]
        ))
        for path in files:
            if not repo.exists(path):
                continue
            tree = repo.tree(path)
            boundary, decl_line = self._boundary_decl(tree)

            # Map every node to its enclosing top-level function.
            encl: dict[int, str] = {}
            top_funcs: dict[str, ast.AST] = {}
            for node in tree.body:
                if isinstance(node, astutil.FunctionNode):
                    top_funcs[node.name] = node
                    for sub in ast.walk(node):
                        encl[id(sub)] = node.name
                elif isinstance(node, ast.ClassDef):
                    for sub in ast.walk(node):
                        encl[id(sub)] = f"{node.name}.<method>"

            used_boundaries: set[str] = set()
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = astutil.dotted_name(node.func)
                last = dotted.split(".")[-1]
                if last not in AXIS_CALLS or not dotted.startswith(
                        ("jax.lax.", "lax.")):
                    continue
                # C2: literal axis names must be declared mesh axes.
                ax = self._axis_arg(node)
                if axes and isinstance(ax, ast.Constant) and isinstance(ax.value, str):
                    if ax.value not in axes:
                        out.append(self.finding(
                            path, node.lineno,
                            f"{last}(..., {ax.value!r}) names a mesh axis "
                            f"not declared in {self.mesh_py} AXES "
                            f"({sorted(axes)}) — it would shard over a "
                            f"ghost axis",
                        ))
                # C3: data-moving collectives need a declared boundary.
                if last not in COLLECTIVES:
                    continue
                owner = encl.get(id(node), "<module>")
                if boundary is None:
                    out.append(self.finding(
                        path, node.lineno,
                        f"jax.lax.{last} in {owner} but {path} declares no "
                        f"COLLECTIVE_BOUNDARY — declare the boundary "
                        f"functions so undeclared ICI dependencies can't "
                        f"creep onto the per-token path",
                    ))
                elif owner not in boundary:
                    out.append(self.finding(
                        path, node.lineno,
                        f"jax.lax.{last} in {owner}, which is not in "
                        f"{path}'s COLLECTIVE_BOUNDARY {sorted(boundary)} — "
                        f"collectives belong inside the declared o/down "
                        f"boundary functions only",
                    ))
                else:
                    used_boundaries.add(owner)

            if boundary:
                for name in sorted(boundary):
                    if name not in top_funcs:
                        out.append(self.finding(
                            path, decl_line,
                            f"COLLECTIVE_BOUNDARY names {name!r} but no "
                            f"top-level function of that name exists — "
                            f"stale declaration",
                        ))
                    elif name not in used_boundaries:
                        out.append(self.finding(
                            path, decl_line,
                            f"COLLECTIVE_BOUNDARY names {name!r} but it "
                            f"contains no collective — stale declaration "
                            f"(tighten it or delete it)",
                        ))

            # C2 for PartitionSpec literals everywhere in the file.
            if axes:
                for node in ast.walk(tree):
                    if not _is_spec_call(node):
                        continue
                    for a in node.args:
                        if (isinstance(a, ast.Constant)
                                and isinstance(a.value, str)
                                and a.value not in axes):
                            out.append(self.finding(
                                path, a.lineno if hasattr(a, "lineno")
                                else node.lineno,
                                f"PartitionSpec axis {a.value!r} not "
                                f"declared in {self.mesh_py} AXES "
                                f"({sorted(axes)}) — typo'd axes shard "
                                f"over nothing",
                            ))

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        self._check_names(repo, out)
        axes = self._declared_axes(repo)
        self._check_collectives(repo, axes, out)
        return out
