"""shared-state-race: cross-thread-root attribute conflicts with no
common lock.

The incident class (PR 11): `Metrics.add_gauge_source()` appended to
`self._gauge_sources` from registration threads while `/metrics` renders
iterated it on HTTP handler threads — no lock in common, a
mutation-during-iteration crash waiting for load. The convention the
EventJournal relies on (loop-thread-only ring appends) had no checker at
all. This pass joins the per-function attribute EFFECT SETS
(tools.lint.summaries) with the thread-root reachability model
(tools.lint.threads): an attribute mutated from one root and touched from
another with no lock held in common across the conflicting pair is a
finding.

Precision rules (Python memory model, GIL):

- A **rebind** (`self.x = v`) is an atomic reference swap: rebind-vs-read
  across roots is SILENT (the reader sees the old or the new object, both
  consistent). This is the thread-start/stop handoff idiom
  (`self._thread = Thread(...)`) and flagging it would be noise.
- A **mutate** (`+=`, `d[k] = v`, `.append()`, `del d[k]`) is a
  read-modify-write. On a CONTAINER attribute, each single op is itself
  GIL-atomic — what breaks is ITERATION from another root interleaving
  with a structural mutation ("dict changed size during iteration",
  skipped/duplicated elements), so container conflicts are
  mutate-vs-iterate pairs. The staged-sidecar idiom (locked append +
  unlocked `if not self._staged:` len-peek + locked swap) stays silent by
  construction: the peek is a plain read. On a scalar/object attribute a
  mutate conflicts with unlocked WRITES from another root (lost updates)
  while cross-root reads of a single-writer counter stay silent (a torn
  read of an int cannot happen; `/metrics` reading a slightly stale
  `m_*` is by design).
- Attributes holding synchronization/handoff objects (Lock, Event,
  Condition, Semaphore, queue.Queue and anything `*Queue`) are the
  BLESSED cross-thread idioms — put→get and set→wait carry their own
  happens-before — and are exempt.
- Accesses inside a class's construction methods happen before the object
  is published to any other thread and are exempt (handoff-escape checks
  the publish ordering itself).
- `# thread: single-writer <role>` on an attribute assignment declares a
  deliberately lock-free single-writer slot (the journal ring): writes
  from any OTHER root are findings, cross-root best-effort reads are
  blessed. `# thread: <role>-only` on a def attributes its accesses to
  that root alone (the thread-affinity pass checks the declaration).
- HTTP handler-class instance state (`BaseHTTPRequestHandler` subclasses)
  is per-request/per-thread and exempt.
"""

from __future__ import annotations

import ast
from typing import Optional

from .. import astutil
from ..core import Finding, Pass, Repo
from ..summaries import DEFAULT_SUMMARY_GLOBS
from ..threads import ThreadModel, role_matches, threads_for

_SYNC_CTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier", "local", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
}


def _value_kind(v: ast.AST) -> str:
    """'sync' | 'container' | 'scalar' | 'object' for one assigned value."""
    if isinstance(v, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                      ast.DictComp, ast.SetComp)):
        return "container"
    if isinstance(v, ast.Constant) or (
            isinstance(v, ast.UnaryOp) and isinstance(v.operand, ast.Constant)):
        return "scalar"
    if isinstance(v, ast.Call):
        ctor = astutil.dotted_name(v.func).split(".")[-1]
        if ctor in _SYNC_CTORS or ctor.endswith("Queue"):
            return "sync"
        if ctor in ("list", "dict", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"):
            return "container"
        if ctor in ("int", "float", "bool", "str", "len", "monotonic",
                    "time", "perf_counter"):
            return "scalar"
    return "object"


class SharedStateRacePass(Pass):
    id = "shared-state-race"
    description = (
        "attribute mutated from one thread root and touched from another "
        "with no common lock (the Metrics._gauge_sources incident class)"
    )
    project_wide = True  # roots/effects span files; --since cannot narrow

    def __init__(self, globs=None):
        self.globs = tuple(DEFAULT_SUMMARY_GLOBS if globs is None else globs)

    # ------------- per-class attribute classification ------------- #

    def _attr_kinds(self, model: ThreadModel) -> dict[str, str]:
        """obj id -> sync/container/scalar/object, from every value ever
        assigned to the attribute anywhere in its class (sync wins, then
        container: `self._x = None` in __init__ rebound to a dict later is
        a container)."""
        rank = {"sync": 3, "container": 2, "object": 1, "scalar": 0}
        kinds: dict[str, str] = {}
        for (path, cname), cls in model.graph.classes.items():
            for m in cls.body:
                if not isinstance(m, astutil.FunctionNode):
                    continue
                me = astutil.self_name(m)
                if me is None:
                    continue
                for node in ast.walk(m):
                    if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                        continue
                    if node.value is None:
                        continue
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if not (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == me):
                            continue
                        obj = f"{path}::{cname}.{t.attr}"
                        k = _value_kind(node.value)
                        if obj not in kinds or rank[k] > rank[kinds[obj]]:
                            kinds[obj] = k
        return kinds

    def _construction_fids(self, model: ThreadModel) -> set[str]:
        """Fids that run during their own class's construction — effects
        there happen before the object is shared."""
        out: set[str] = set()
        for (path, cname) in model.graph.classes:
            table = model.graph._methods.get((path, cname), {})
            nodes = {n: model.graph.funcs[f].node for n, f in table.items()}
            for name in astutil.construction_methods(nodes):
                out.add(table[name])
        return out

    # ------------- the pass ------------- #

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        model = threads_for(repo, self.globs)
        idx = model.idx
        kinds = self._attr_kinds(model)
        construction = self._construction_fids(model)
        handler_cls = {f"{p}::{c}." for (p, c) in model._handler_classes()}
        roots = {r.role: r for r in model.roots}

        # obj -> role -> [(effect, fid)]
        acc: dict[str, dict[str, list]] = {}
        for root in model.roots:
            for fid in model.reach(root):
                s = idx.summaries.get(fid)
                if s is None or not s.effects:
                    continue
                decl = model.affinity.get(fid)
                if decl is not None and not role_matches(decl[0], root):
                    # Declared single-owner: the thread-affinity pass
                    # reports foreign reachability; attributing the
                    # effects here too would double-report every access.
                    continue
                base = fid.split("@")[0].rsplit(".", 1)[0] if "@" in fid else fid
                in_ctor = fid in construction or base in construction
                for e in s.effects:
                    if in_ctor and e.obj.startswith(f"{s.path}::{s.cls}."):
                        continue  # pre-publication
                    if any(e.obj.startswith(h) for h in handler_cls):
                        continue  # per-request handler instance state
                    acc.setdefault(e.obj, {}).setdefault(
                        root.role, []).append((e, fid))

        def fname(fid: str) -> str:
            s = idx.summaries.get(fid)
            if s is None:
                return fid
            return f"{s.cls + '.' if s.cls else ''}{s.name}()"

        def short(obj: str) -> str:
            path, _, qual = obj.partition("::")
            return f"{path.rsplit('/', 1)[-1]}::{qual}"

        for obj in sorted(acc):
            byrole = acc[obj]
            if obj in model.instance_owned:
                # Each instance is owned by one thread at a time (per-
                # request objects; ownership transfers by pop/queue) —
                # class-granularity conflicts are cross-instance noise.
                continue
            sw = model.single_writer.get(obj)
            if sw is not None:
                declared, dpath, dline = sw
                for role in sorted(byrole):
                    if role_matches(declared, roots[role]):
                        continue
                    for e, fid in sorted(byrole[role],
                                         key=lambda p: p[0].line):
                        if e.kind in ("rebind", "mutate"):
                            out.append(self.finding(
                                e.obj.partition("::")[0], e.line,
                                f"{short(obj)} is declared `# thread: "
                                f"single-writer {declared}` "
                                f"({dpath}:{dline}) but {fname(fid)} "
                                f"writes it from thread root "
                                f"'{role}' — the lock-free slot has "
                                f"exactly one blessed writer",
                            ))
                            break
                continue
            kind = kinds.get(obj, "container" if "." not in
                             obj.partition("::")[2] else "object")
            if kind == "sync":
                continue
            mutates = []
            for role in sorted(byrole):
                for e, fid in byrole[role]:
                    if e.kind == "mutate":
                        mutates.append((role, e, fid))
            if not mutates:
                continue
            mutates.sort(key=lambda t: (t[0], t[1].line))
            hit: Optional[tuple] = None
            for roleA, e1, fid1 in mutates:
                for roleB in sorted(byrole):
                    if hit:
                        break
                    same = roleB == roleA
                    if same and not roots[roleA].multi:
                        continue
                    for e2, fid2 in sorted(byrole[roleB],
                                           key=lambda p: p[0].line):
                        if e2 is e1:
                            continue
                        if same and fid2 == fid1:
                            # Two instances of a multi root in the SAME
                            # function: overwhelmingly per-instance state
                            # (each pump/handler works its own object).
                            # Cross-function same-role conflicts (one
                            # handler registers, another iterates) stand.
                            continue
                        if kind == "container":
                            if e2.kind != "iterate":
                                continue  # single container ops are
                                #           GIL-atomic; iteration is not
                        elif e2.kind in ("read", "iterate"):
                            continue  # stale-read-tolerant scalar scrape
                        if set(e1.held) & set(e2.held):
                            continue
                        hit = (roleA, e1, fid1, roleB, e2, fid2, same)
                        break
                if hit:
                    break
            if not hit:
                continue
            roleA, e1, fid1, roleB, e2, fid2, same = hit
            verb = {"read": "read", "iterate": "iterated",
                    "rebind": "written", "mutate": "mutated"}[e2.kind]
            other = (f"another '{roleB}' thread" if same
                     else f"thread root '{roleB}'")
            out.append(self.finding(
                e1.obj.partition("::")[0], e1.line,
                f"{short(obj)} ({kind}) mutated by {fname(fid1)} on thread "
                f"root '{roleA}' and {verb} by {fname(fid2)} on {other} "
                f"(line {e2.line}) with no lock in common — hold one lock "
                f"across both sides, hand off through a queue, or declare "
                f"`# thread: single-writer <role>` if the slot is "
                f"deliberately lock-free",
            ))
        return out
