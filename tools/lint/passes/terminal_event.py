"""terminal-event: dropping a request reference without posting a terminal
event.

The repeated hang class (bitten in PR 1 *and* PR 4): a code path removes an
entry from the engine's pending queue — or deactivates a slot — without
posting a "done"/"error" event, and the consumer blocks on its token queue
forever (BENCH_r05 burned 30 minutes of tier-1 exactly this way; the
watchdog busy-kill inside the admission gap did it again in PR 1).

Rule, per class (default Engine): every method that DROPS a request
reference —

  * removes from `self._pending` (`popleft()` / `pop()` / `remove()` /
    `clear()` or a rebind of `self._pending`), or
  * deactivates a slot (`self.slots[i] = None`)

— must be "terminal-safe": it posts a terminal event itself (a
`*._q.put(TokenEvent(kind="done"|"error", ...))` or a call to a method that
does, transitively), or EVERY intra-class caller of it is terminal-safe
(helpers like `_release` are owned by posting callers). Re-enqueues
(`appendleft`/`append` back onto the queue) are not drops. A method that
fails the rule is a hang waiting for its code path to be hit.

Since ISSUE 20 the self-posting arm runs on the exception-edge CFG
(tools.lint.cfg): a drop site inside a posting method is only safe when
some post point is CFG-connected to it — the post reachable from the drop,
or the drop reachable from a post (post-then-remove order), or a
re-enqueue. The check is existential ("some path balances") rather than
resource-leak's universal one: a drop whose post sits in a possibly-zero-
iteration loop is the drain idiom, not a hang. What the CFG adds is
catching a drop on an early-return or handler path that can never meet the
method's post — the exact shape the pre-CFG "posts anywhere in the body"
rule waved through.
"""

from __future__ import annotations

import ast
from typing import Optional

from .. import astutil
from ..cfg import ast_parents, build_cfg
from ..core import Finding, Pass, Repo

DEFAULT_TARGETS = [
    ("localai_tpu/engine/engine.py", "Engine", "_pending", "slots"),
    # Cluster dispatch (ISSUE 6): the scheduler layer holds caller handles
    # in its own _pending map — the same hang class applies one level up.
    ("localai_tpu/cluster/scheduler.py", "ClusterClient", "_pending", "slots"),
    # Trace store (ISSUE 11): live traces may only leave `_live` through
    # `retire()` — the trace-side analogue of posting a terminal event
    # (retire is invoked exactly by RequestTrace.terminal). A fifth tuple
    # element names such sanctioned terminal-marker methods.
    ("localai_tpu/observe/trace.py", "TraceStore", "_live", "slots",
     ("retire",)),
]

_REMOVE_CALLS = {"popleft", "pop", "remove", "clear"}


def _terminal_put_in(fn) -> bool:
    """True when fn contains `<x>._q.put(TokenEvent(kind='done'|'error'))`
    or `<x>.put(TokenEvent(...))` with a terminal kind."""
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "put" and node.args):
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Call)
                and astutil.dotted_name(arg.func).split(".")[-1] == "TokenEvent"):
            continue
        kind: Optional[str] = None
        if arg.args and isinstance(arg.args[0], ast.Constant):
            kind = arg.args[0].value
        for kw in arg.keywords:
            if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                kind = kw.value.value
        if kind in ("done", "error"):
            return True
    return False


def _drop_sites(fn, me: str, pending_attr: str, slots_attr: str):
    """[(ast node, line, what)] for statements that drop a request
    reference."""
    out = []
    for node in ast.walk(fn):
        # self._pending.popleft() / .pop() / .remove() / .clear()
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REMOVE_CALLS
                and isinstance(node.func.value, ast.Attribute)
                and isinstance(node.func.value.value, ast.Name)
                and node.func.value.value.id == me
                and node.func.value.attr == pending_attr):
            out.append((node, node.lineno,
                        f"{pending_attr}.{node.func.attr}()"))
        # rebind: self._pending = <...> (including tuple unpacking)
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for tt in ast.walk(t):
                    if (isinstance(tt, ast.Attribute)
                            and isinstance(tt.ctx, ast.Store)
                            and isinstance(tt.value, ast.Name)
                            and tt.value.id == me
                            and tt.attr == pending_attr):
                        out.append((node, node.lineno,
                                    f"{pending_attr} rebind"))
            # slot deactivation: self.slots[i] = None
            if (isinstance(node.value, ast.Constant)
                    and node.value.value is None):
                for t in node.targets:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Attribute)
                            and isinstance(t.value.value, ast.Name)
                            and t.value.value.id == me
                            and t.value.attr == slots_attr):
                        out.append((node, node.lineno,
                                    f"{slots_attr}[...] = None"))
    return out


def _node_local_exprs(node):
    """The expressions a CFG node itself evaluates (compound statements'
    bodies belong to their own nodes)."""
    s = node.stmt
    if s is None:
        return []
    if node.kind == "branch":
        if isinstance(s, (ast.If, ast.While)):
            return [s.test]
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return [s.iter]
        if isinstance(s, ast.Match):
            return [s.subject]
        return []
    if isinstance(s, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in s.items]
    if isinstance(s, ast.ExceptHandler):
        return []
    return [s]


def _is_post(expr, me: str, posting: set, pending_attr: str) -> bool:
    """Does this expression post terminally: a direct terminal put, a call
    to a (transitively) posting method, or a re-enqueue onto the queue?"""
    if _terminal_put_in(expr):
        return True
    for sub in ast.walk(expr):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if not isinstance(f, ast.Attribute):
            continue
        if (isinstance(f.value, ast.Name) and f.value.id == me
                and f.attr in posting):
            return True
        if (f.attr in ("append", "appendleft")
                and isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == me
                and f.value.attr == pending_attr):
            return True
    return False


def _stmt_node_idxs(cfg, parents, node) -> list[int]:
    """CFG node indices of the statement enclosing an arbitrary AST node."""
    n = node
    while n is not None and id(n) not in cfg.stmt_nodes:
        n = parents.get(id(n))
    return list(cfg.stmt_nodes.get(id(n), ())) if n is not None else []


def _reachable(cfg, starts) -> set[int]:
    seen = set(starts)
    stack = list(starts)
    while stack:
        i = stack.pop()
        for dst, _kind in cfg.succ[i]:
            if dst not in seen:
                seen.add(dst)
                stack.append(dst)
    return seen


class TerminalEventPass(Pass):
    id = "terminal-event"
    description = (
        "pending-queue removal / slot deactivation on a path that never "
        "posts a terminal event (caller hangs forever)"
    )

    def __init__(self, targets=None):
        self.targets = DEFAULT_TARGETS if targets is None else targets

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for target in self.targets:
            path, class_name, pending_attr, slots_attr = target[:4]
            # Optional fifth element: method names that ARE the sanctioned
            # terminal marker for this class (ISSUE 11: TraceStore.retire
            # plays the role TokenEvent puts play for the engine).
            markers = set(target[4]) if len(target) > 4 else set()
            if not repo.exists(path) or not repo.in_scope(path):
                continue
            cls = repo.find_class(path, class_name)
            if cls is None:
                continue
            methods = astutil.methods_of(cls)

            # 1. Methods that post a terminal event, transitively through
            #    intra-class calls.
            posts = {m for m, fn in methods.items()
                     if m in markers or _terminal_put_in(fn)}
            changed = True
            while changed:
                changed = False
                for m, fn in methods.items():
                    if m in posts:
                        continue
                    if astutil.self_calls(fn) & posts:
                        posts.add(m)
                        changed = True

            # 2. Intra-class caller graph.
            callers: dict[str, set[str]] = {m: set() for m in methods}
            for m, fn in methods.items():
                for callee in astutil.self_calls(fn):
                    if callee in callers:
                        callers[callee].add(m)

            # 3. terminal-safe = posts, or all callers terminal-safe.
            safe = set(posts)
            changed = True
            while changed:
                changed = False
                for m in methods:
                    if m in safe:
                        continue
                    cs = callers[m]
                    if cs and cs <= safe:
                        safe.add(m)
                        changed = True

            construction = astutil.construction_methods(methods)
            posting = posts | markers
            for mname, fn in methods.items():
                me = astutil.self_name(fn)
                if me is None or mname in construction:
                    continue  # no consumer exists during construction
                if mname in markers:
                    continue  # the sanctioned terminal marker itself
                sites = _drop_sites(fn, me, pending_attr, slots_attr)
                if not sites:
                    continue
                cs = callers[mname]
                if cs and cs <= safe:
                    continue  # helper owned by terminal-safe callers
                if mname not in safe:
                    for _node, line, what in sites:
                        out.append(self.finding(
                            path, line,
                            f"{class_name}.{mname}() drops a request "
                            f"reference ({what}) but neither it nor all of "
                            f"its callers post a terminal TokenEvent — the "
                            f"consumer blocks on its stream forever (the "
                            f"PR 1/PR 4 hang class)",
                        ))
                    continue
                # The method posts (directly or transitively): each drop
                # must be CFG-connected to some post point.
                cfg = build_cfg(fn)
                parents = ast_parents(fn)
                post_idxs = {
                    idx for idx, node in enumerate(cfg.nodes)
                    if any(_is_post(e, me, posting, pending_attr)
                           for e in _node_local_exprs(node))
                }
                post_fwd = _reachable(cfg, post_idxs)
                for node, line, what in sites:
                    drop_idxs = _stmt_node_idxs(cfg, parents, node)
                    if not drop_idxs:
                        continue
                    fwd = _reachable(cfg, drop_idxs)
                    if fwd & post_idxs or any(d in post_fwd
                                              for d in drop_idxs):
                        continue
                    out.append(self.finding(
                        path, line,
                        f"{class_name}.{mname}() drops a request reference "
                        f"({what}) on a path that neither reaches nor "
                        f"follows any of its terminal posts — on that path "
                        f"the consumer blocks on its stream forever (the "
                        f"PR 1/PR 4 hang class)",
                    ))
        return out
