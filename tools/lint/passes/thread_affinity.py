"""thread-affinity: `# thread: <role>-only` declarations, checked.

The EventJournal's lock-free ring append is safe because exactly one
thread (the engine loop) ever calls it — a convention that, before this
pass, lived in a docstring. A declaration comment on the def makes the
ownership machine-checked:

    # thread: engine-loop-only
    def append(self, event, ...):

Findings:
- the declared function is REACHABLE (through the interprocedural call
  graph) from any thread root that does not match the declared role —
  the convention is being violated, or the graph got a new edge nobody
  noticed;
- the declared role matches NO discovered thread root (stale declaration:
  the role was renamed or deleted — an unchecked comment is worse than
  none);
- same staleness check for `# thread: single-writer <role>` attribute
  declarations (enforced by shared-state-race; validated here).

Declared functions are excluded from the `main` root's public-entry
surface — the declaration IS the statement that callers on arbitrary
threads must not call it — so the check bites exactly when a real call
chain from another root exists.
"""

from __future__ import annotations

from ..core import Finding, Pass, Repo
from ..summaries import DEFAULT_SUMMARY_GLOBS
from ..threads import role_matches, threads_for


class ThreadAffinityPass(Pass):
    id = "thread-affinity"
    description = (
        "`# thread: <role>-only` declaration violated (reachable from a "
        "foreign thread root) or stale (no such root)"
    )
    project_wide = True

    def __init__(self, globs=None):
        self.globs = tuple(DEFAULT_SUMMARY_GLOBS if globs is None else globs)

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        model = threads_for(repo, self.globs)
        roles = sorted({r.role for r in model.roots})

        for fid in sorted(model.affinity):
            declared, path, line = model.affinity[fid]
            matched = [r for r in model.roots if role_matches(declared, r)]
            if not matched:
                out.append(self.finding(
                    path, line,
                    f"`# thread: {declared}-only` names no discovered "
                    f"thread root (known roots: {', '.join(roles)}) — "
                    f"the role was renamed or removed; fix or drop the "
                    f"declaration",
                ))
                continue
            for root in model.roots:
                if role_matches(declared, root):
                    continue
                if fid in model.reach(root):
                    s = model.idx.summaries.get(fid)
                    where = (f"{s.cls + '.' if s and s.cls else ''}"
                             f"{s.name if s else fid}")
                    out.append(self.finding(
                        path, line,
                        f"{where}() is declared `# thread: {declared}-only` "
                        f"but is reachable from thread root '{root.role}' "
                        f"— a foreign thread can enter the single-owner "
                        f"path; break the call chain or widen the "
                        f"declaration",
                    ))
        for obj in sorted(model.single_writer):
            declared, path, line = model.single_writer[obj]
            if not any(role_matches(declared, r) for r in model.roots):
                out.append(self.finding(
                    path, line,
                    f"`# thread: single-writer {declared}` on "
                    f"{obj.partition('::')[2]} names no discovered thread "
                    f"root (known roots: {', '.join(roles)}) — stale "
                    f"declaration",
                ))
        return out
