"""trace-safety: host syncs and recompile triggers where they serialize the
pipeline.

TPU serving lives or dies on keeping the host out of the per-token critical
path ("Ragged Paged Attention", arxiv 2604.15464; pjit training at scale,
arxiv 2204.06514: one stray device→host sync serializes the whole pipeline).
Three bug classes, three sub-checks:

1. TRACED MODULES (localai_tpu/ops/*.py, localai_tpu/models/llama.py —
   everything there runs under jit/pjit or inside a Pallas kernel): flag
   `.item()` / `.tolist()` / `.block_until_ready()` / `jax.device_get` /
   `np.asarray`-on-traced, `int()`/`float()`/`bool()` of a traced local, and
   Python `if`/`while`/`assert` branching on a traced value (use `jnp.where`
   / `lax.cond`). "Traced" is inferred by local dataflow: a name assigned
   from a jnp/lax/jax.random call, or arithmetic/indexing thereof. numpy on
   STATIC values (building trace-time constants, e.g. rope tables) is fine
   and not flagged.

2. ENGINE HOT PATH (the decode/admission methods of Engine): flag
   `.item()` / `.tolist()` / `block_until_ready` / `jax.device_get`, and
   `np.asarray` / `np.array` whose argument references a device-resident
   root (self.cache/rngs/counts/bias/d_tokens/d_positions/d_gstate/d_cache,
   or an entry's toks/tk/lp). Host-side numpy on python lists is fine.
   Known-good sync points (the drainer-backed inline pull) carry
   suppressions with written reasons.

3. RECOMPILE TRIGGERS: inside the hot path, array constructors
   (jnp.zeros/ones/full/empty/arange) whose shape derives from a per-call
   Python value (a local not derived from self.cfg/self.ecfg constants) —
   every distinct value compiles a new program. Intentional per-(m, bucket)
   program families carry suppressions documenting that contract.
"""

from __future__ import annotations

import ast
from typing import Optional

from .. import astutil
from ..core import Finding, Pass, Repo

TRACED_MODULE_GLOBS = [
    "localai_tpu/ops/*.py",
    "localai_tpu/models/llama.py",
    # The cluster layer is host-side BY CONTRACT (it sits on every dispatch
    # path): any jnp/lax value it manufactures — and then branches on or
    # pulls — is a sync the scheduler would pay per request.
    "localai_tpu/cluster/*.py",
    # The parallel layer traces inside every sharded program (shard_map
    # bodies, ring rotation) — a host sync here stalls ALL chips (ISSUE 7).
    "localai_tpu/parallel/*.py",
    # The observability layer (ISSUE 11) rides the engine loop between
    # every dispatch: journal appends, trace notes, timeline/postmortem
    # reads must never sync the device. observe/fence.py and
    # observe/profile.py are EXCLUDED by design — they are the declared
    # sync/measurement points (LOCALAI_TRACE_FENCE / LOCALAI_PROFILE),
    # exactly like the engine drainer thread is excluded from HOT_METHODS.
    "localai_tpu/observe/journal.py",
    "localai_tpu/observe/trace.py",
    "localai_tpu/observe/timeline.py",
    "localai_tpu/observe/postmortem.py",
    # Prompt-lookup drafting (ISSUE 12): the suffix index runs on the
    # engine loop between every dispatch — it must stay pure Python/numpy
    # (a traced value or device pull here stalls the whole decode cadence).
    "localai_tpu/engine/speclookup.py",
]

ENGINE_TARGET = ("localai_tpu/engine/engine.py", "Engine")

# The decode/admission steady state: every loop iteration flows through
# these. Excluded by design: warmup (pre-traffic), preemption/swap
# (_preempt_youngest, _swap_*_pages — declared drain points where the loop
# has already quiesced the device), and the drainer thread (its whole job
# is to host-sync off the critical path).
HOT_METHODS = {
    "_loop", "_admit_pending", "_purge_pending", "_enforce_deadlines",
    "_advance_chunked", "_chunk_start", "_dispatch_chunk_mid",
    "_dispatch_chunk_final", "_dispatch_admit", "_dispatch_admit_cached",
    "_dispatch_resume_swap", "_dispatch_block", "_dispatch_spec_block",
    "_process_entry", "_post_token", "_finish", "_release",
    "_grow_for_decode", "_pages_grow_slot", "_pages_alloc", "_pages_free",
    "_pick_block_size", "_has_unscheduled", "_charge", "_track",
    "_note_admitted", "_grammar_choose", "_grammar_advance",
    # Speculative scheduling (ISSUE 12): planning + lookup mining run
    # between every dispatch; the sd-sync walks per-slot state each round.
    "_spec_plan", "_spec_len_for", "_lookup_propose", "_spec_sd_sync",
}

DEVICE_ROOTS = {
    "cache", "d_cache", "counts", "rngs", "bias", "d_tokens", "d_positions",
    "d_gstate", "toks", "tk", "lp",
}

def _walk_scope(fn):
    """Walk a function's own body without descending into nested defs —
    nested functions are visited as scopes of their own (with their own
    traced-locals inference), so flagging them here would double-report."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (*astutil.FunctionNode, ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


_TRACED_CALL_ROOTS = ("jnp", "lax", "jax")
_SYNC_METHOD_CALLS = {"item", "tolist", "block_until_ready"}
_SHAPE_CTORS = {"jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty",
                "jnp.arange"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _is_traced_call(node: ast.AST) -> bool:
    """Calls that produce traced values: jnp.* / lax.* and the value-level
    jax namespaces. Host-side jax introspection (default_backend, devices,
    config, debug) does not count."""
    if not isinstance(node, ast.Call):
        return False
    name = astutil.dotted_name(node.func)
    if name.startswith(("jnp.", "lax.")):
        return True
    return name.startswith(("jax.lax.", "jax.nn.", "jax.numpy.",
                            "jax.random.", "jax.scipy."))


def _traced_locals(fn) -> set[str]:
    """Names assigned (directly or via arithmetic/indexing) from jnp/lax
    calls within this function. Two fixpoint rounds cover the chains that
    occur in practice."""
    traced: set[str] = set()

    def expr_traced(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if _is_traced_call(sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in traced:
                return True
        return False

    for _ in range(2):
        for node in _walk_scope(fn):
            if isinstance(node, ast.Assign) and expr_traced(node.value):
                for t in node.targets:
                    for tt in ast.walk(t):
                        if isinstance(tt, ast.Name):
                            traced.add(tt.id)
            elif isinstance(node, ast.AugAssign) and expr_traced(node.value):
                if isinstance(node.target, ast.Name):
                    traced.add(node.target.id)
    return traced


def _test_is_static(node: ast.AST) -> bool:
    """True when every Name/Attribute in a branch test resolves through
    static metadata (.shape/.ndim/.dtype/len()) or plain python values —
    conservative: only attribute chains ending in static attrs count."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr not in _STATIC_ATTRS:
            return False
    return True


class TraceSafetyPass(Pass):
    id = "trace-safety"
    description = (
        "host sync / python-branch-on-traced / per-request recompile "
        "trigger in trace-context or engine hot-path code"
    )

    def __init__(self, traced_globs=None, engine_target=None,
                 hot_methods=None):
        self.traced_globs = (TRACED_MODULE_GLOBS if traced_globs is None
                             else traced_globs)
        self.engine_target = (ENGINE_TARGET if engine_target is None
                              else engine_target)
        self.hot_methods = HOT_METHODS if hot_methods is None else hot_methods

    # ---------------- traced modules ---------------- #

    def _check_traced_fn(self, path: str, fn, out: list[Finding]) -> None:
        traced = _traced_locals(fn)

        def is_traced_expr(node: ast.AST) -> bool:
            for sub in ast.walk(node):
                if _is_traced_call(sub):
                    return True
                if isinstance(sub, ast.Name) and sub.id in traced:
                    return True
            return False

        for node in _walk_scope(fn):
            if isinstance(node, ast.Call):
                name = astutil.dotted_name(node.func)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_METHOD_CALLS):
                    out.append(self.finding(
                        path, node.lineno,
                        f".{node.func.attr}() inside trace-context code — "
                        f"a device→host sync that serializes the pipeline "
                        f"(and a TracerError under jit)",
                    ))
                elif name in ("jax.device_get", "jax.block_until_ready"):
                    out.append(self.finding(
                        path, node.lineno,
                        f"{name}() inside trace-context code — host sync",
                    ))
                elif (name in ("np.asarray", "np.array", "numpy.asarray",
                               "numpy.array")
                      and node.args and is_traced_expr(node.args[0])):
                    out.append(self.finding(
                        path, node.lineno,
                        f"{name}() of a traced value — device→host pull "
                        f"inside trace-context code (use jnp)",
                    ))
                elif (name in ("int", "float", "bool") and node.args
                      and isinstance(node.args[0], ast.Name)
                      and node.args[0].id in traced):
                    out.append(self.finding(
                        path, node.lineno,
                        f"{name}(...) of traced local "
                        f"{node.args[0].id!r} — concretizes a tracer "
                        f"(host sync / TracerError)",
                    ))
            elif isinstance(node, (ast.If, ast.While)):
                if is_traced_expr(node.test) and not _test_is_static(node.test):
                    out.append(self.finding(
                        path, node.lineno,
                        "python branch on a traced value — use jnp.where / "
                        "lax.cond / lax.select (branching concretizes the "
                        "tracer; at best a recompile per outcome, at worst "
                        "a TracerBoolConversionError)",
                    ))
            elif isinstance(node, ast.Assert):
                if is_traced_expr(node.test) and not _test_is_static(node.test):
                    out.append(self.finding(
                        path, node.lineno,
                        "assert on a traced value — concretizes the tracer; "
                        "use checkify or move the check to the host caller",
                    ))

    # ---------------- engine hot path ---------------- #

    def _expr_touches_device(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr in DEVICE_ROOTS:
                return True
        return False

    def _static_locals(self, fn) -> set[str]:
        """Names assigned only from constants or self.cfg/self.ecfg/self.plan
        attribute chains — per-engine constants, safe as shapes."""
        static: set[str] = set()
        dynamic: set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            ok = True
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id not in ("self",):
                    if sub.id not in static:
                        ok = False
                elif isinstance(sub, ast.Attribute):
                    root = astutil.dotted_name(sub)
                    if not root.startswith(("self.cfg", "self.ecfg",
                                            "self.plan", "self._max_pages")):
                        ok = False
            for t in node.targets:
                if isinstance(t, ast.Name):
                    (static if ok and t.id not in dynamic else dynamic).add(t.id)
                    if not ok:
                        static.discard(t.id)
        return static

    def _check_hot_method(self, path: str, mname: str, fn,
                          out: list[Finding]) -> None:
        static = self._static_locals(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.dotted_name(node.func)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_METHOD_CALLS
                    and (node.func.attr == "block_until_ready"
                         or self._expr_touches_device(node.func.value))):
                # .item()/.tolist() on host numpy (already-drained entry
                # results, request fields) is free; only receivers rooted
                # at device-resident state are syncs.
                out.append(self.finding(
                    path, node.lineno,
                    f".{node.func.attr}() in engine hot path "
                    f"({mname}) — blocking device→host sync on the "
                    f"decode/admission critical path",
                ))
            elif name in ("jax.device_get", "jax.block_until_ready"):
                out.append(self.finding(
                    path, node.lineno,
                    f"{name}() in engine hot path ({mname}) — blocking "
                    f"device sync; results should flow through the drainer "
                    f"thread / _host_copy_async instead",
                ))
            elif (name in ("np.asarray", "np.array") and node.args
                  and self._expr_touches_device(node.args[0])):
                out.append(self.finding(
                    path, node.lineno,
                    f"{name}() of a device value in engine hot path "
                    f"({mname}) — synchronous device→host pull; route it "
                    f"through the drainer thread or _host_copy_async",
                ))
            elif name in _SHAPE_CTORS and node.args:
                shape = node.args[0]
                dyn = [
                    sub.id for sub in ast.walk(shape)
                    if isinstance(sub, ast.Name) and sub.id != "self"
                    and sub.id not in static
                ]
                if dyn:
                    out.append(self.finding(
                        path, node.lineno,
                        f"{name}() in engine hot path ({mname}) with shape "
                        f"from per-call value(s) {sorted(set(dyn))} — every "
                        f"distinct value compiles a new XLA program "
                        f"(recompile trigger); bucket it or hoist it",
                    ))

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for path in repo.files(*self.traced_globs):
            if not repo.in_scope(path):
                continue  # --since incremental mode
            for node in ast.walk(repo.tree(path)):
                if isinstance(node, astutil.FunctionNode):
                    self._check_traced_fn(path, node, out)
        epath, ecls = self.engine_target
        if repo.exists(epath) and repo.in_scope(epath):
            cls = repo.find_class(epath, ecls)
            if cls is not None:
                for mname, fn in astutil.methods_of(cls).items():
                    if mname in self.hot_methods:
                        self._check_hot_method(epath, mname, fn, out)
        return out
