"""Declarative resource-protocol registry + CFG lifecycle verification.

ISSUE 20: every recent incident was one bug class — a resource acquired
and not resolved on some exit path. The PR 19 breaker probe slot leaked on
the HTTPError edge of `call_with_retry`; the `pick(reserve=True)` →
`end_stream` inflight window leaked on early-continue edges; the PR 1/PR 4
terminal-event hangs were pending-entry drops without a posted event. This
module makes the protocol the DECLARATION and the checking generic:

- `Protocol` names acquire primitives (with how the acquisition is
  conditioned on the return value), resolve primitives, and transfer/escape
  forms. Adding a protocol is adding a declaration here — no pass code.
- `find_acquisitions` locates acquire sites in a function body.
- `FlowAnalysis` runs the acquisition forward over the exception-edge CFG
  (tools.lint.cfg): every path from the acquire must hit a resolve or a
  transfer before EXIT / RAISE_EXIT. Path sensitivity comes from a small
  fact store over simple comparisons (`x is None`, `x == "probe"`,
  `code in (404, 409)`, truthiness) with an implication oracle, so the
  infeasible `in (404,409)`-False-then-`== 404`-True path in netspan does
  not produce a false leak. The first leaking path found is reported with a
  line-numbered witness trace (the Finding.witness field).

Consumed by the resource-leak / double-resolve / counter-balance passes and
by the CFG rewrite of page-refcount; `tools/chaos_run.py` reads
JOURNAL_BALANCE to tie each declared protocol to runtime journal evidence.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import deque
from typing import Callable, Optional

from . import astutil
from .callgraph import FuncDef
from .cfg import CFG, build_cfg, dominating_tests
from .summaries import KNOWN_RAISERS, SummaryIndex

# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AcqSpec:
    """One acquire primitive: calling `call` acquires the resource when the
    return value satisfies `mode` ("always" | "truthy" | "not_none" |
    "eq" against eq_value). `token` names where the handle lives: "ret"
    (the assigned name), "arg0" (first positional arg — begin_stream(name),
    _pages_addref(pages)), or "recv" (the receiver itself —
    self._lock.acquire())."""

    call: str
    mode: str = "always"
    eq_value: object = None
    token: str = "ret"
    self_only: bool = False          # only `self.<call>(...)` matches
    kwarg_gate: tuple = ()           # ("reserve", True): kwarg must equal
    recv_hint: str = ""              # receiver last segment must contain
    carry_arg0: bool = False         # arg0 also identifies the acquisition
    #                                  (_pages_alloc(slot_idx, …): cleanup
    #                                  is keyed by the slot index)


@dataclasses.dataclass(frozen=True)
class Protocol:
    pid: str
    what: str                        # human name of the resource
    acquires: tuple
    resolves: tuple = ()             # token-matched resolve call names
    blanket_resolves: tuple = ()     # resolve regardless of arguments
    transfer_attrs: tuple = ()       # self.<attr> stores WITH token = owner
    blanket_transfer_attrs: tuple = ()  # any store/mutator on these = owner
    owner_methods: tuple = ()        # primitive impls themselves: exempt
    owner_classes: tuple = ()        # whole classes exempt (the provider)
    strict: bool = True              # double-resolve applies (clamp-and-heal
    #                                  protocols declare strict=False)
    journal: tuple = ()              # (begin event, (end events…)) — chaos
    #                                  harness balance check (JOURNAL_BALANCE)


KV_PAGES = Protocol(
    pid="kv-pages", what="kv page block",
    acquires=(
        AcqSpec("_pages_alloc", "not_none", token="ret", self_only=True,
                carry_arg0=True),
        AcqSpec("_pages_claim", "not_none", token="ret", self_only=True),
        AcqSpec("_pages_addref", "always", token="arg0", self_only=True),
    ),
    resolves=("_pages_release",),
    blanket_resolves=("_pages_free",),
    transfer_attrs=("_slot_pages", "h_ptable", "_prefix_entries",
                    "_prefix_host"),
    blanket_transfer_attrs=("slots", "_slot_pages", "h_ptable", "_pending",
                            "_prefix_entries", "_prefix_host"),
    owner_methods=("_pages_alloc", "_pages_claim", "_pages_addref",
                   "_pages_release", "_pages_free"),
    strict=True,
)

BREAKER_PROBE = Protocol(
    pid="breaker-probe", what="circuit-breaker half-open probe slot",
    acquires=(
        AcqSpec("guard", "truthy", token="ret"),
        AcqSpec("admit", "eq", eq_value="probe", token="ret"),
    ),
    # record_success / record_failure / release_probe resolve whatever probe
    # is in flight — and are ordinary accounting when none is (clamp-and-
    # heal by design), hence blanket + strict=False.
    blanket_resolves=("record_success", "record_failure", "release_probe"),
    owner_classes=("CircuitBreaker",),
    strict=False,
    journal=("breaker_probe", ("breaker_close", "breaker_open")),
)

SCHED_INFLIGHT = Protocol(
    pid="sched-inflight", what="scheduler inflight reservation",
    acquires=(
        AcqSpec("pick", "not_none", token="ret",
                kwarg_gate=("reserve", True)),
        AcqSpec("begin_stream", "always", token="arg0"),
    ),
    resolves=("end_stream",),
    owner_classes=("ClusterScheduler",),
    strict=True,
)

ADAPTER_PIN = Protocol(
    pid="adapter-pin", what="adapter weight pin",
    acquires=(
        AcqSpec("_adapter_acquire", "truthy", token="ret", self_only=True),
    ),
    resolves=("_adapter_unpin",),
    transfer_attrs=("h_adapter",),
    owner_methods=("_adapter_acquire", "_adapter_unpin"),
    strict=True,
)

LOCK_MANUAL = Protocol(
    pid="lock-manual", what="manually-paired lock",
    acquires=(
        # Only receivers named like locks: `self._lock.acquire()`. Lease
        # accounting that happens to use acquire/release names (e.g.
        # server/manager.py LoadedModel) is a different protocol and is
        # deliberately not matched.
        AcqSpec("acquire", "always", token="recv", recv_hint="lock"),
    ),
    resolves=("release",),
    strict=True,
)

NET_HANDLE = Protocol(
    pid="net-handle", what="network stream handle",
    acquires=(AcqSpec("urlopen", "always", token="ret"),),
    resolves=("close",),
    strict=False,  # close() is idempotent
)

PROTOCOLS: tuple = (KV_PAGES, BREAKER_PROBE, SCHED_INFLIGHT, ADAPTER_PIN,
                    LOCK_MANUAL, NET_HANDLE)

# Chaos-harness contract (ISSUE 20 satellite): protocols whose lifecycle is
# journaled must show balance in the event stream after every scenario —
# each begin event eventually followed by one of its end events. Runtime
# evidence for the same declarations the static passes verify.
JOURNAL_BALANCE = {
    p.pid: p.journal for p in PROTOCOLS if p.journal
}


# ---------------------------------------------------------------------------
# Acquisition discovery
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Acquisition:
    spec: AcqSpec
    protocol: Protocol
    stmt: ast.AST            # the statement anchoring the acquire
    call: ast.Call
    line: int
    token: Optional[str]     # primary handle name (None = anonymous)
    in_test: bool = False    # call sits in an if/while test
    test_polarity: Optional[bool] = None  # held on the True (or False) edge


def _call_parts(call: ast.Call) -> tuple[str, str]:
    """(last name, dotted receiver) of a call."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr, astutil.dotted_name(f.value)
    if isinstance(f, ast.Name):
        return f.id, ""
    return "", ""


def _spec_matches(call: ast.Call, spec: AcqSpec, me: Optional[str]) -> bool:
    name, recv = _call_parts(call)
    if name != spec.call:
        return False
    if spec.self_only and (me is None or recv != me):
        return False
    if spec.recv_hint and spec.recv_hint not in recv.split(".")[-1].lower():
        return False
    if spec.kwarg_gate:
        k, v = spec.kwarg_gate
        for kw in call.keywords:
            if (kw.arg == k and isinstance(kw.value, ast.Constant)
                    and kw.value.value == v):
                break
        else:
            return False
    return True


def _stmt_iter(fn) -> list[ast.AST]:
    """Every statement in the function body, nested defs not descended."""
    out: list[ast.AST] = []
    stack = list(fn.body)
    while stack:
        s = stack.pop()
        out.append(s)
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for field in ("body", "orelse", "finalbody"):
            stack.extend(getattr(s, field, None) or [])
        for h in getattr(s, "handlers", None) or []:
            stack.extend(h.body)
        for c in getattr(s, "cases", None) or []:
            stack.extend(c.body)
    return out


def find_acquisitions(fn, me: Optional[str],
                      protocols) -> list[Acquisition]:
    out: list[Acquisition] = []
    with_managed: set[int] = set()
    for s in _stmt_iter(fn):
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        # `with urlopen(...) as resp:` — __exit__ resolves
                        # on every unwind; never a leak.
                        with_managed.add(id(sub))
    for s in _stmt_iter(fn):
        for proto in protocols:
            for spec in proto.acquires:
                acq = _match_acquire(s, spec, proto, me, with_managed)
                if acq is not None:
                    out.append(acq)
    return out


def _match_acquire(s: ast.AST, spec: AcqSpec, proto: Protocol,
                   me: Optional[str],
                   with_managed: set[int]) -> Optional[Acquisition]:
    def token_for(call: ast.Call, assigned: Optional[str]) -> Optional[str]:
        if spec.token == "ret":
            return assigned
        if spec.token == "arg0":
            if call.args and isinstance(call.args[0], ast.Name):
                return call.args[0].id
            return None
        if spec.token == "recv":
            return _call_parts(call)[1] or None
        return None

    if isinstance(s, ast.Assign) and isinstance(s.value, ast.Call):
        call = s.value
        if id(call) not in with_managed and _spec_matches(call, spec, me):
            assigned = (s.targets[0].id
                        if len(s.targets) == 1
                        and isinstance(s.targets[0], ast.Name) else None)
            return Acquisition(spec, proto, s, call, s.lineno,
                               token_for(call, assigned))
    elif isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
        call = s.value
        if id(call) not in with_managed and _spec_matches(call, spec, me):
            return Acquisition(spec, proto, s, call, s.lineno,
                               token_for(call, None))
    elif isinstance(s, (ast.If, ast.While)):
        # `if self._pages_claim(n) is None:` / `if breaker.allow():` — the
        # branch itself is the acquire; heldness is an edge polarity.
        got = _test_acquire(s.test, spec, me, with_managed)
        if got is not None:
            call, polarity = got
            return Acquisition(spec, proto, s, call, s.lineno, None,
                               in_test=True, test_polarity=polarity)
    elif isinstance(s, ast.Return) and s.value is not None:
        # `return self._pages_claim(n)` — ownership transfers to the caller
        # in the same statement; nothing to track.
        return None
    return None


def _test_acquire(test: ast.expr, spec: AcqSpec, me,
                  with_managed) -> Optional[tuple[ast.Call, bool]]:
    """(call, polarity): resource held on the `polarity` edge of the test."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        got = _test_acquire(test.operand, spec, me, with_managed)
        if got:
            return got[0], not got[1]
        return None
    if isinstance(test, ast.Call):
        if id(test) not in with_managed and _spec_matches(test, spec, me) \
                and spec.mode in ("truthy", "not_none", "always"):
            return test, True
        return None
    if (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.left, ast.Call)
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
            and spec.mode == "not_none"):
        call = test.left
        if id(call) in with_managed or not _spec_matches(call, spec, me):
            return None
        if isinstance(test.ops[0], (ast.Is, ast.Eq)):
            return call, False   # `claim() is None` true ⇒ NOT held
        if isinstance(test.ops[0], (ast.IsNot, ast.NotEq)):
            return call, True
    return None


# ---------------------------------------------------------------------------
# Facts: atoms over simple comparisons, with an implication oracle
# ---------------------------------------------------------------------------

# Atom forms (name is always a plain local):
#   ("truthy", name)        bool(name)
#   ("isnone", name)        name is None
#   ("eq", name, const)     name == const (const not None)
#   ("in", name, consts)    name in (c1, c2, …)
#   ("opaque", text)        whole-test fallback (call-free tests only)


def _parse_atom(test: ast.expr):
    """(atom, invert) with test-truth == atom-truth XOR invert, or None."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        got = _parse_atom(test.operand)
        return (got[0], not got[1]) if got else None
    if isinstance(test, ast.Name):
        return ("truthy", test.id), False
    if (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.left, ast.Name)):
        name, op, right = test.left.id, test.ops[0], test.comparators[0]
        if isinstance(right, ast.Constant):
            if right.value is None:
                if isinstance(op, (ast.Is, ast.Eq)):
                    return ("isnone", name), False
                if isinstance(op, (ast.IsNot, ast.NotEq)):
                    return ("isnone", name), True
                return None
            if isinstance(op, (ast.Eq, ast.Is)):
                return ("eq", name, right.value), False
            if isinstance(op, (ast.NotEq, ast.IsNot)):
                return ("eq", name, right.value), True
            return None
        if isinstance(right, (ast.Tuple, ast.List, ast.Set)) and isinstance(
                op, (ast.In, ast.NotIn)):
            vals = tuple(e.value for e in right.elts
                         if isinstance(e, ast.Constant))
            if len(vals) == len(right.elts):
                return ("in", name, vals), isinstance(op, ast.NotIn)
    if not any(isinstance(sub, ast.Call) for sub in ast.walk(test)):
        try:
            return ("opaque", ast.unparse(test)), False
        except Exception:
            return None
    return None


def _atom_names(atom) -> tuple[str, ...]:
    if atom[0] == "opaque":
        return ()
    return (atom[1],)


def _eval_atom(atom, facts: dict) -> Optional[bool]:
    """Truth of `atom` under `facts` (atom -> bool), via implications."""
    if atom in facts:
        return facts[atom]
    kind = atom[0]
    if kind == "opaque":
        return None
    name = atom[1]
    for known, val in facts.items():
        if known[0] == "opaque" or known[1] != name:
            continue
        k = known[0]
        if kind == "eq":
            c = atom[2]
            if k == "eq" and val and known[2] != c:
                return False
            if k == "isnone" and val:
                return False
            if k == "in" and not val and c in known[2]:
                return False
            if k == "in" and val and c not in known[2]:
                return False
            if k == "truthy" and not val and bool(c):
                return False
        elif kind == "in":
            S = atom[2]
            if k == "eq" and val:
                return known[2] in S
            if k == "isnone" and val:
                return None in S
        elif kind == "isnone":
            if k == "eq" and val and known[2] is not None:
                return False
            if k == "truthy" and val:
                return False
        elif kind == "truthy":
            if k == "isnone" and val:
                return False
            if k == "eq" and val:
                return bool(known[2])
    return None


class _TokenInfo:
    """Which local names carry the acquisition handle, under which
    semantics. `held_false(facts)` answers: do the facts PROVE the handle
    was never acquired / already dropped on this path?"""

    def __init__(self, mode: str, eq_value=None):
        self.mode = mode
        self.eq_value = eq_value
        self.truthy: set[str] = set()    # truthiness == heldness
        self.eq: set[str] = set()        # == eq_value means held
        self.none: set[str] = set()      # is None means NOT held
        self.carries: set[str] = set()   # container copies: carry the
        #                                  handle for transfer/resolve
        #                                  matching, no heldness semantics

    def all_names(self) -> set[str]:
        return self.truthy | self.eq | self.none | self.carries

    def held_false(self, facts: dict) -> bool:
        for n in self.truthy:
            if _eval_atom(("truthy", n), facts) is False:
                return True
        for n in self.none:
            if _eval_atom(("isnone", n), facts) is True:
                return True
        for n in self.eq:
            if _eval_atom(("eq", n, self.eq_value), facts) is False:
                return True
        return False


def token_info_for(fn, acq: Acquisition) -> _TokenInfo:
    """Flow-insensitive alias closure: `held = admission == "probe"` makes
    `held` a truthy-alias of an eq-mode token; `x = tok` copies class."""
    ti = _TokenInfo(acq.spec.mode, acq.spec.eq_value)
    tok = acq.token
    if tok is None:
        return ti
    if acq.spec.mode == "truthy":
        ti.truthy.add(tok)
    elif acq.spec.mode == "eq":
        ti.eq.add(tok)
    elif acq.spec.mode == "not_none":
        ti.none.add(tok)
        ti.truthy.add(tok)  # `if row:` on a page list refines too
    else:
        ti.truthy.add(tok)  # "always": truthiness tests are vacuous but
        #                      a `tok = False` kill is still a drop signal
    if (acq.spec.carry_arg0 and acq.call is not None and acq.call.args
            and isinstance(acq.call.args[0], ast.Name)):
        ti.carries.add(acq.call.args[0].id)
    changed = True
    while changed:
        changed = False
        for s in _stmt_iter(fn):
            if not (isinstance(s, ast.Assign) and len(s.targets) == 1
                    and isinstance(s.targets[0], ast.Name)):
                continue
            t = s.targets[0].id
            v = s.value
            if isinstance(v, ast.Name) and v.id in ti.all_names():
                for group in (ti.truthy, ti.eq, ti.none):
                    if v.id in group and t not in group:
                        group.add(t)
                        changed = True
            elif (acq.spec.mode == "eq" and isinstance(v, ast.Compare)
                  and len(v.ops) == 1 and isinstance(v.ops[0], ast.Eq)
                  and isinstance(v.left, ast.Name) and v.left.id in ti.eq
                  and isinstance(v.comparators[0], ast.Constant)
                  and v.comparators[0].value == acq.spec.eq_value
                  and t not in ti.truthy):
                ti.truthy.add(t)
                changed = True
            elif (_is_container_copy(v) and t not in ti.carries
                  and any(isinstance(x, ast.Name) and x.id in ti.all_names()
                          for x in ast.walk(v))):
                # entry = {"pages": list(pages)} / pair = (dst, row): the
                # container carries the handle — storing IT somewhere is
                # storing the handle.
                ti.carries.add(t)
                changed = True
    return ti


def _is_container_copy(v: ast.expr) -> bool:
    if isinstance(v, (ast.Dict, ast.List, ast.Tuple, ast.Set)):
        return True
    return (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
            and v.func.id in ("list", "tuple", "set", "sorted", "frozenset",
                              "dict"))


# ---------------------------------------------------------------------------
# Per-node classification
# ---------------------------------------------------------------------------


def _local_exprs(node) -> list:
    """The code a CFG node itself executes (compound bodies excluded)."""
    s = node.stmt
    if s is None:
        return []
    if node.kind == "branch":
        if isinstance(s, (ast.If, ast.While)):
            return [s.test]
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return [s.iter]
        if isinstance(s, ast.Match):
            return [s.subject]
        return []
    if isinstance(s, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in s.items]
    if isinstance(s, ast.ExceptHandler):
        return []
    return [s]


def _assigned_names(node) -> set[str]:
    s = node.stmt
    out: set[str] = set()
    if s is None:
        return out
    if node.kind == "branch" and isinstance(s, (ast.For, ast.AsyncFor)):
        for sub in ast.walk(s.target):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
        return out
    if node.kind == "branch":
        return out
    if isinstance(s, ast.ExceptHandler):
        if s.name:
            out.add(s.name)
        return out
    if isinstance(s, (ast.With, ast.AsyncWith)):
        for i in s.items:
            if i.optional_vars is not None:
                for sub in ast.walk(i.optional_vars):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
        return out
    if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = s.targets if isinstance(s, ast.Assign) else [s.target]
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
    return out


# Mutators through which a handle can escape into a container the caller
# (or a later loop in the same function) owns and drains.
_CONTAINER_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "put",
})


class _Classifier:
    """Protocol-specific meaning of one CFG node: resolve / transfer /
    token kill. Built once per (acquisition, function)."""

    def __init__(self, proto: Protocol, spec: AcqSpec, ti: _TokenInfo,
                 me: Optional[str], extra_blanket_resolves: tuple = (),
                 acq_call: Optional[ast.Call] = None):
        self.proto = proto
        self.spec = spec
        self.ti = ti
        self.me = me
        self.extra_blanket = frozenset(extra_blanket_resolves)
        self.acq_call = acq_call

    def resolve_at(self, node) -> Optional[tuple[str, int]]:
        """("resolve"|"blanket", line) when this node resolves the
        acquisition."""
        names = self.ti.all_names()
        for expr in _local_exprs(node):
            for sub in ast.walk(expr):
                if not isinstance(sub, ast.Call) or sub is self.acq_call:
                    continue
                cname, recv = _call_parts(sub)
                if cname in self.proto.blanket_resolves or \
                        cname in self.extra_blanket:
                    return ("blanket", sub.lineno)
                if cname in self.proto.resolves:
                    if not names:
                        return ("blanket", sub.lineno)
                    if recv in names:
                        return ("resolve", sub.lineno)
                    for a in sub.args:
                        if isinstance(a, ast.Name) and a.id in names:
                            return ("resolve", sub.lineno)
        return None

    def transfers_at(self, node) -> bool:
        names = self.ti.all_names()
        s = node.stmt
        if (node.kind == "branch" and isinstance(s, (ast.For, ast.AsyncFor))
                and names
                and any(isinstance(x, ast.Name) and x.id in names
                        for x in ast.walk(s.iter))
                and self._distributes(s)):
            # Distributing loop (`for p, c in zip(fresh, cols): pages[c]=p`):
            # installing each element transfers the whole collection. Safe
            # to anchor at the loop head — a zero-iteration run means the
            # collection is empty, so there is nothing to leak.
            return True
        for expr in _local_exprs(node):
            for sub in ast.walk(expr):
                if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
                    v = getattr(sub, "value", None)
                    if v is not None and names and any(
                            isinstance(x, ast.Name) and x.id in names
                            for x in ast.walk(v)):
                        return True
                if isinstance(sub, ast.Call):
                    f = sub.func
                    if (isinstance(f, ast.Attribute)
                            and f.attr in _CONTAINER_MUTATORS):
                        attr = self._owned_attr(f.value)
                        token_in_args = names and any(
                            isinstance(a, ast.Name) and a.id in names
                            for x in sub.args for a in ast.walk(x))
                        if attr is not None:
                            if attr in self.proto.blanket_transfer_attrs:
                                return True
                            if attr in self.proto.transfer_attrs and \
                                    token_in_args:
                                return True
                        elif (isinstance(f.value, ast.Name)
                              and f.value.id != self.me and token_in_args):
                            # Handle stashed into a LOCAL container
                            # (`forked.append((dst, row))`): ownership
                            # escapes to whoever drains the list — the
                            # cleanup loop there is that path's resolve.
                            return True
        if isinstance(s, ast.Assign) and node.kind != "branch":
            for t in s.targets:
                attr = self._store_attr(t)
                if attr is None:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id != self.me and names and any(
                                isinstance(x, ast.Name) and x.id in names
                                for x in ast.walk(s.value))):
                        return True  # local[i] = token: same local escape
                    continue
                if attr in self.proto.blanket_transfer_attrs:
                    return True
                if attr in self.proto.transfer_attrs and names and any(
                        isinstance(x, ast.Name) and x.id in names
                        for x in ast.walk(s.value)):
                    return True
        return False

    def _distributes(self, loop) -> bool:
        """Does the loop body install a loop-target element into a
        subscript store (local table alias or tracked self attribute)?"""
        targets = {x.id for x in ast.walk(loop.target)
                   if isinstance(x, ast.Name)}
        tracked = (set(self.proto.transfer_attrs)
                   | set(self.proto.blanket_transfer_attrs))
        for stmt in loop.body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Assign):
                    continue
                if not any(isinstance(x, ast.Name) and x.id in targets
                           for x in ast.walk(sub.value)):
                    continue
                for t in sub.targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if base is t:
                        continue  # not a subscript store
                    if isinstance(base, ast.Name) and base.id != self.me:
                        return True
                    if (isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and (self.me is None or base.value.id == self.me)
                            and base.attr in tracked):
                        return True
        return False

    def _owned_attr(self, recv) -> Optional[str]:
        """attr name when `recv` is `self.<attr>` or `self.<attr>[i]`."""
        if isinstance(recv, ast.Subscript):
            recv = recv.value
        if (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and (self.me is None or recv.value.id == self.me)):
            return recv.attr
        return None

    def _store_attr(self, target) -> Optional[str]:
        if isinstance(target, ast.Subscript):
            target = target.value
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and (self.me is None or target.value.id == self.me)):
            return target.attr
        return None

    def kills_token(self, node) -> bool:
        """Non-constant reassignment of a token name (re-acquire, re-guard,
        handle replaced): this acquisition stops being trackable — prune.
        Constant assigns become facts instead; alias definitions are not
        kills."""
        s = node.stmt
        if not (node.kind == "stmt" and isinstance(s, ast.Assign)
                and len(s.targets) == 1
                and isinstance(s.targets[0], ast.Name)):
            return False
        t = s.targets[0].id
        if t not in self.ti.all_names():
            return False
        v = s.value
        if isinstance(v, ast.Constant):
            return False
        if isinstance(v, ast.Name) and v.id in self.ti.all_names():
            return False  # alias copy
        if isinstance(v, ast.Compare):
            return False  # alias definition (held = admission == "probe")
        return True


# ---------------------------------------------------------------------------
# The flow analysis
# ---------------------------------------------------------------------------

_EXC_EDGES = ("except", "raise")
_ANNOTATED = ("except", "raise", "return", "break", "continue", "finally")
_MAX_STATES = 60000
_MAX_FACTS = 12


@dataclasses.dataclass
class FlowIssue:
    kind: str          # "leak" | "double"
    line: int          # acquisition line
    exit_line: int     # line of the exit / second resolve
    exit_kind: str     # "exit" | "raise-exit" | resolve detail
    witness: list
    first_resolve: int = 0


class FlowAnalysis:
    """Forward exploration of one acquisition over the CFG."""

    def __init__(self, cfg: CFG, path: str, fn, acq: Acquisition,
                 classifier: _Classifier, mode: str = "leak"):
        self.cfg = cfg
        self.path = path
        self.fn = fn
        self.acq = acq
        self.cls = classifier
        self.mode = mode
        self.ti = classifier.ti
        # Branch-consistency tracking is restricted to names that matter:
        # token/alias names plus names compared in 2+ parseable tests.
        counts: dict[str, int] = {}
        for s in _stmt_iter(fn):
            test = getattr(s, "test", None)
            if test is None:
                continue
            for part in self._conjuncts(test):
                got = _parse_atom(part)
                if got:
                    for n in _atom_names(got[0]):
                        counts[n] = counts.get(n, 0) + 1
        self.tracked = {n for n, c in counts.items() if c >= 2}
        self.tracked |= self.ti.all_names()

    @staticmethod
    def _conjuncts(test: ast.expr) -> list[ast.expr]:
        if isinstance(test, ast.BoolOp):
            out = []
            for v in test.values:
                out.extend(FlowAnalysis._conjuncts(v))
            return out
        return [test]

    # ---------------- facts ---------------- #

    def _seed_facts(self) -> dict:
        facts: dict = {}
        for test, polarity in dominating_tests(self.fn, self.acq.stmt):
            self._record_test(test, polarity, facts)
        # Note for opaque/complex dominating tests nothing is recorded —
        # sound: fewer known facts, more paths explored.
        return facts

    def _record_test(self, test: ast.expr, value: bool, facts: dict) -> None:
        if isinstance(test, ast.BoolOp):
            if isinstance(test.op, ast.And) and value:
                for v in test.values:
                    self._record_test(v, True, facts)
            elif isinstance(test.op, ast.Or) and not value:
                for v in test.values:
                    self._record_test(v, False, facts)
            return
        got = _parse_atom(test)
        if got is None:
            return
        atom, invert = got
        names = _atom_names(atom)
        if atom[0] != "opaque" and not all(n in self.tracked for n in names):
            return
        if len(facts) < _MAX_FACTS or atom in facts:
            facts[atom] = value ^ invert

    def _eval_test(self, test: ast.expr, facts: dict) -> Optional[bool]:
        if isinstance(test, ast.BoolOp):
            vals = [self._eval_test(v, facts) for v in test.values]
            if isinstance(test.op, ast.And):
                if any(v is False for v in vals):
                    return False
                if all(v is True for v in vals):
                    return True
                return None
            if any(v is True for v in vals):
                return True
            if all(v is False for v in vals):
                return False
            return None
        got = _parse_atom(test)
        if got is None:
            return None
        atom, invert = got
        val = _eval_atom(atom, facts)
        return None if val is None else val ^ invert

    def _branch_facts(self, test: ast.expr, edge_true: bool,
                      facts: dict) -> Optional[dict]:
        """Facts after taking the true/false edge; None = edge infeasible."""
        known = self._eval_test(test, facts)
        if known is not None and known != edge_true:
            return None
        out = dict(facts)
        if isinstance(test, ast.BoolOp):
            vals = [(v, self._eval_test(v, facts)) for v in test.values]
            if isinstance(test.op, ast.And):
                if edge_true:
                    for v, _ in vals:
                        self._record_test(v, True, out)
                else:
                    unknown = [v for v, val in vals if val is None]
                    if len(unknown) == 1:
                        # the rest are known True: the single unknown
                        # conjunct is what failed
                        self._record_test(unknown[0], False, out)
            else:  # Or
                if not edge_true:
                    for v, _ in vals:
                        self._record_test(v, False, out)
                else:
                    unknown = [v for v, val in vals if val is None]
                    if len(unknown) == 1:
                        self._record_test(unknown[0], True, out)
            return out
        self._record_test(test, edge_true, out)
        return out

    def _invalidate(self, facts: dict, names: set[str]) -> dict:
        if not names:
            return facts
        out = {a: v for a, v in facts.items()
               if not (set(_atom_names(a)) & names)
               and not (a[0] == "opaque" and any(n in a[1] for n in names))}
        return out

    def _const_assign_facts(self, node, facts: dict) -> dict:
        s = node.stmt
        if not (node.kind == "stmt" and isinstance(s, ast.Assign)
                and len(s.targets) == 1
                and isinstance(s.targets[0], ast.Name)
                and isinstance(s.value, ast.Constant)):
            return facts
        name = s.targets[0].id
        if name not in self.tracked:
            return facts
        v = s.value.value
        out = dict(facts)
        if len(out) >= _MAX_FACTS:
            return facts
        out[("truthy", name)] = bool(v)
        if v is None:
            out[("isnone", name)] = True
        elif isinstance(v, (int, str, float, bool)):
            out[("isnone", name)] = False
            out[("eq", name, v)] = True
        return out

    # ---------------- the walk ---------------- #

    def run(self) -> list[FlowIssue]:
        cfg = self.cfg
        anchors = cfg.stmt_nodes.get(id(self.acq.stmt), [])
        if not anchors:
            return []
        issues: list[FlowIssue] = []
        facts0 = self._seed_facts()
        start = anchors[0]
        initial: list[tuple] = []
        if self.acq.in_test:
            # the acquire is a branch test: held only on the polarity edge
            want = "true" if self.acq.test_polarity else "false"
            for dst, kind in cfg.succ[start]:
                if kind == want:
                    nf = self._branch_facts(
                        cfg.nodes[start].test, self.acq.test_polarity, facts0
                    ) if cfg.nodes[start].test is not None else dict(facts0)
                    if nf is not None:
                        initial.append((dst, kind, "maybe", nf))
        else:
            # exceptional edges out of the acquire itself: nothing acquired
            for dst, kind in cfg.succ[start]:
                if kind not in _EXC_EDGES:
                    initial.append((dst, kind, "maybe", dict(facts0)))
        seen: set = set()
        parent: dict = {}
        queue: deque = deque()
        for dst, kind, hs, facts in initial:
            st = (dst, hs, frozenset(facts.items()))
            if st not in seen:
                seen.add(st)
                parent[st] = (None, kind)
                queue.append((st, facts))
        while queue:
            if len(seen) > _MAX_STATES:
                return issues  # blown budget: stay silent, never FP
            (node_idx, hs, _fkey), facts = st_facts = queue.popleft()
            st = (node_idx, hs, _fkey)
            node = cfg.nodes[node_idx]
            if node.kind in ("exit", "raise-exit"):
                if hs == "maybe" and self.mode == "leak":
                    issues.append(FlowIssue(
                        kind="leak", line=self.acq.line,
                        exit_line=self._witness_line(st, parent),
                        exit_kind=node.kind,
                        witness=self._witness(st, parent)))
                    return issues  # first (shortest) witness is the report
                continue
            # --- node effects (normal continuation) --- #
            resolved_here = None
            transferred = False
            killed = False
            if node.stmt is not None:
                resolved_here = self.cls.resolve_at(node)
                transferred = self.cls.transfers_at(node)
                killed = self.cls.kills_token(node)
            post_hs = hs
            skip_normal = False
            if resolved_here is not None:
                rkind, rline = resolved_here
                if hs == "maybe":
                    if self.mode == "leak":
                        skip_normal = True  # resolved: this path is done
                    elif rkind == "resolve" and self.cls.proto.strict:
                        post_hs = ("resolved", rline)
                    else:
                        skip_normal = True
                else:  # already resolved
                    if rkind == "resolve":
                        issues.append(FlowIssue(
                            kind="double", line=self.acq.line,
                            exit_line=rline, exit_kind="double-resolve",
                            witness=self._witness(st, parent),
                            first_resolve=hs[1]))
                        return issues
                    skip_normal = True
            if transferred or killed:
                skip_normal = True
            assigned = _assigned_names(node) if node.stmt is not None else set()
            for dst, kind in cfg.succ[node_idx]:
                if kind in _EXC_EDGES:
                    if resolved_here is not None and hs == "maybe":
                        # The resolver itself raised: the resolution attempt
                        # still happened — whatever went wrong inside the
                        # primitive is the primitive's bug, not this
                        # caller's leak.
                        continue
                    # exception DURING the stmt: effects did not complete
                    nf = self._invalidate(facts, assigned & self.tracked)
                    self._push(dst, hs, nf, st, kind, seen, parent, queue)
                    continue
                if skip_normal:
                    continue
                if node.kind == "branch" and kind in ("true", "false") \
                        and node.test is not None:
                    nf = self._branch_facts(node.test, kind == "true", facts)
                    if nf is None:
                        continue  # infeasible edge
                else:
                    nf = dict(facts)
                nf = self._invalidate(nf, assigned & self.tracked)
                nf = self._const_assign_facts(node, nf)
                new_hs = post_hs
                if new_hs == "maybe" and self.ti.held_false(nf):
                    continue  # proven not-held on this path
                self._push(dst, new_hs, nf, st, kind, seen, parent, queue)
        return issues

    def _push(self, dst, hs, facts, prev, kind, seen, parent, queue):
        st = (dst, hs, frozenset(facts.items()))
        if st in seen:
            return
        seen.add(st)
        parent[st] = (prev, kind)
        queue.append((st, facts))

    # ---------------- witness ---------------- #

    def _witness(self, st, parent) -> list[str]:
        chain = []
        cur = st
        while cur is not None:
            prev, kind = parent.get(cur, (None, "next"))
            chain.append((cur[0], kind))
            cur = prev
        chain.reverse()
        out = [f"{self.path}:{self.acq.line}"]
        last_line = self.acq.line
        for node_idx, kind in chain:
            node = self.cfg.nodes[node_idx]
            line = node.line or last_line
            last_line = line
            if node.kind == "exit":
                entry = f"{self.path}:{line} (exit)" if kind not in _ANNOTATED \
                    else f"{self.path}:{line} ({kind})"
            elif node.kind == "raise-exit":
                entry = f"{self.path}:{line} ({kind})"
            elif kind in _ANNOTATED:
                entry = f"{self.path}:{line} ({kind})"
            elif node.kind in ("join",):
                continue
            else:
                entry = f"{self.path}:{line}"
            if not out or out[-1] != entry:
                out.append(entry)
        return out

    def _witness_line(self, st, parent) -> int:
        prev, _ = parent.get(st, (None, "next"))
        while prev is not None:
            node = self.cfg.nodes[prev[0]]
            if node.line:
                return node.line
            prev = parent.get(prev, (None, "next"))[0]
        return self.acq.line


# ---------------------------------------------------------------------------
# Repo-level helpers for the passes
# ---------------------------------------------------------------------------


def releasing_methods(methods: dict) -> set[str]:
    """Class methods that transitively reach a kv release primitive
    (`_pages_release`/`_pages_free`) through intra-class calls — calling
    one is a blanket resolve for kv-pages acquisitions (the engine's
    `_resume_discard` teardown shape)."""
    out = set()
    for m, fn in methods.items():
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and _call_parts(node)[0] in (
                    "_pages_release", "_pages_free"):
                out.add(m)
                break
    changed = True
    while changed:
        changed = False
        for m, fn in methods.items():
            if m in out:
                continue
            if astutil.self_calls(fn) & out:
                out.add(m)
                changed = True
    return out


def cfg_for(repo, index: SummaryIndex, fd: FuncDef) -> CFG:
    """Exception-edge CFG for one function, cached on the Repo. Raise edges
    for out-of-try calls come from the interprocedural may-raise fixpoint
    (plus KNOWN_RAISERS); a --since run rebuilds only the changed files'
    CFGs while the fixpoint stays full."""
    cache = getattr(repo, "_cfgs", None)
    if cache is None:
        cache = repo._cfgs = {}
    key = (id(fd.node), id(index))
    if key in cache:
        return cache[key]
    may = index.may_raise()
    ltypes = index.graph.local_types(fd.path, fd.node)

    def call_may_raise(call: ast.Call) -> bool:
        if astutil.dotted_name(call.func).split(".")[-1] in KNOWN_RAISERS:
            return True
        cands = index.graph.resolve(fd, call, local_types=ltypes)
        return any(may.get(c) for c in cands)

    cache[key] = build_cfg(fd.node, call_may_raise)
    return cache[key]


def analyze_protocol(repo, index: SummaryIndex, fd: FuncDef,
                     protocols, mode: str = "leak",
                     extra_blanket_resolves: tuple = ()) -> list[FlowIssue]:
    """All lifecycle issues for one function under the given protocols."""
    me = astutil.self_name(fd.node) if fd.cls else None
    acquisitions = find_acquisitions(fd.node, me, protocols)
    if not acquisitions:
        return []
    cfg = cfg_for(repo, index, fd)
    out: list[FlowIssue] = []
    for acq in acquisitions:
        if fd.cls and fd.cls in acq.protocol.owner_classes:
            continue
        if fd.name in acq.protocol.owner_methods:
            continue
        ti = token_info_for(fd.node, acq)
        classifier = _Classifier(acq.protocol, acq.spec, ti, me,
                                 extra_blanket_resolves, acq.call)
        issues = FlowAnalysis(cfg, fd.path, fd.node, acq, classifier,
                              mode=mode).run()
        for iss in issues:
            iss.protocol = acq.protocol  # type: ignore[attr-defined]
        out.extend(issues)
    return out
