"""Per-function summaries for interprocedural passes (ISSUE 8).

One walk per function computes everything the new passes consume, cached on
the Repo next to the AST/module cache so the consuming passes (and a
--since rerun) share a single build:

  - locks:     which locks a function ACQUIRES (`with self.lock:` /
               `with MODULE_LOCK:`), which locks are held AT each
               acquisition and at each call site, and which locks a
               `*_locked` method assumes held on entry (the repo convention:
               caller holds the class lock). Lock identity is
               "path::Class.attr" (or "path::NAME" for module locks) — one
               id per lock OBJECT SLOT, which is the granularity deadlock
               ordering is about.
  - calls:     resolved candidate callees (tools.lint.callgraph) with the
               held-lock set, for the lock-order fixpoint.
  - rng keys:  whether a key-named parameter is consumed (passed to a
               jax.random sampler or split/fold_in) — callers treat passing
               a key to such a helper as one consumption of that key.
  - donation:  whether the function returns a `jax.jit(..., donate_argnums=...)`
               callable and which positions are ALWAYS donated (the literal
               base tuple; conditional extensions are not claimed).
  - effects:   attribute EFFECT SETS (ISSUE 15) — every `self.attr` /
               typed-receiver attribute / module-global access the function
               makes, with the held-lock set at the access and a kind:
               "read" (a Load), "rebind" (the slot is re-pointed — a
               GIL-atomic reference swap), or "mutate" (read-modify-write:
               AugAssign, subscript store/delete, or a mutator method like
               .append()/.update() called on the attribute). The
               shared-state-race / thread-affinity / handoff-escape passes
               join these with the thread-root reachability model
               (tools.lint.threads) to find cross-thread conflicts.

The fixpoint (`may_acquire`) propagates lock acquisition up the call graph
until stable, which is what turns "this function takes a lock" into "this
call may take that lock while you hold yours" — the lock-order edge.

Nested `def`s are summarized too (synthetic fid `{parent}::{name}@{line}`
under `SummaryIndex.nested_defs`): `threading.Thread(target=work)` bodies
are real thread roots, and their effects/locks must not vanish just
because the function is a closure. A nested def inside a method inherits
the enclosing receiver name, so its `self.x` accesses resolve; its
held-set starts EMPTY (it runs later, on another thread — a `with lock:`
around the `def` statement does not protect the body).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from . import astutil
from .callgraph import CallGraph, FuncDef, callgraph_for
from .core import Repo

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

# The union of every interprocedural pass's default targets: passes running
# with DEFAULT scope share ONE SummaryIndex build under this key instead of
# each building their own (fixture runs with custom globs still get their
# own small index). The thread-model passes (ISSUE 15) widened this to the
# full threaded surface: server/, observe/, explorer/, services/, gallery/.
DEFAULT_SUMMARY_GLOBS = (
    "localai_tpu/engine/*.py",
    "localai_tpu/server/*.py",
    "localai_tpu/federation/router.py",
    "localai_tpu/cluster/*.py",
    "localai_tpu/observe/*.py",
    "localai_tpu/explorer/*.py",
    "localai_tpu/services/*.py",
    "localai_tpu/gallery/*.py",
    "localai_tpu/models/*.py",
    "localai_tpu/ops/*.py",
    "localai_tpu/parallel/*.py",
    "localai_tpu/train/*.py",
)

# jax.random functions that CONSUME a key. `split` is a consumer (splitting
# the same key twice yields the same children — the canonical correlated-
# streams bug); `fold_in` is NOT (fold_in(key, i) with varying data is the
# blessed way to derive many independent keys from one base).
KEY_CONSUMERS = {
    "normal", "uniform", "categorical", "gumbel", "bernoulli", "randint",
    "truncated_normal", "permutation", "choice", "exponential", "laplace",
    "gamma", "beta", "dirichlet", "poisson", "rademacher", "bits",
    "split",
}
KEY_PARAM_NAMES = {"key", "rng", "rngs", "prng_key", "base_key"}

# Method names that MUTATE their receiver in place. Calling one of these on
# `self.attr` is a read-modify-write of shared structure — the
# `_gauge_sources.append()` vs `/metrics` iterate incident class (PR 11) —
# and is recorded as a "mutate" effect, unlike a plain Load.
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "add", "update",
    "setdefault", "sort", "reverse", "rotate", "fill", "subtract",
})

# Containers whose constructor at module level makes a name a tracked
# module-global mutable (functions reading/mutating it are effects).
_CONTAINER_CTORS = frozenset({
    "list", "dict", "set", "deque", "defaultdict", "OrderedDict", "Counter",
})

# Call names that raise without any `raise` statement visible to this
# analysis — the seeds of the may-raise fixpoint besides explicit raises
# (ISSUE 20). Deliberately minimal: urlopen is the repo's entire network
# surface (URLError/HTTPError on every transfer), and that is the exception
# class the resource passes exist for. `faults.fire` raises too, but only
# under injected chaos — treating it as a raiser would put exception edges
# on every hot-path statement; the chaos harness's journal-balance check
# covers fault-path leaks at runtime instead.
KNOWN_RAISERS = frozenset({"urlopen"})


def _is_assert_raise(node: ast.Raise) -> bool:
    """`raise AssertionError(...)` — the allocator's clamp-and-heal debug
    raises (gated on LOCALAI_ALLOC_DEBUG). Programmer-error crashes, not
    exit paths resource protocols must survive; excluded from seeds, the
    same way `assert` statements get no exception edge in the CFG."""
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    return isinstance(exc, ast.Name) and exc.id == "AssertionError"


def _handlers_catch_all(handlers: list) -> bool:
    for h in handlers:
        if h.type is None:
            return True
        elts = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        for e in elts:
            name = e.id if isinstance(e, ast.Name) else getattr(e, "attr", "")
            if name in ("Exception", "BaseException"):
                return True
    return False


def escape_info(fn) -> tuple[bool, frozenset[int]]:
    """(raises directly, lines of calls whose exceptions ESCAPE fn) — both
    ignoring anything sitting under an except-all barrier (`except:` /
    `except Exception` / `except BaseException`), which is how a handler
    cuts may-raise propagation. A bare `raise` inside a handler counts as a
    seed when the handler itself is not barriered: re-raising IS escaping.
    """
    seed = False
    lines: set[int] = set()

    def walk(node: ast.AST, barriered: bool) -> None:
        nonlocal seed
        if isinstance(node, ast.Try):
            inner = barriered or _handlers_catch_all(node.handlers)
            for ch in node.body:
                walk(ch, inner)
            # Handler and else bodies are NOT protected by this try's own
            # handlers; finally runs on the way out either way.
            for h in node.handlers:
                for ch in h.body:
                    walk(ch, barriered)
            for ch in node.orelse + node.finalbody:
                walk(ch, barriered)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return
        if isinstance(node, ast.Raise) and not barriered:
            if not _is_assert_raise(node):
                seed = True
        if isinstance(node, ast.Call) and not barriered:
            lines.add(node.lineno)
            if astutil.dotted_name(node.func).split(".")[-1] in KNOWN_RAISERS:
                seed = True
        for ch in ast.iter_child_nodes(node):
            walk(ch, barriered)

    for stmt in fn.body:
        walk(stmt, False)
    return seed, frozenset(lines)


def module_mutables(tree: ast.Module) -> set[str]:
    """Module-level names bound to mutable containers — the module-global
    half of the effect-set model. Constants (UPPER_CASE tuples/strings) and
    rebindable scalars are not tracked; container identity is what threads
    share."""
    out: set[str] = set()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        is_container = isinstance(v, (ast.List, ast.Dict, ast.Set,
                                      ast.ListComp, ast.DictComp, ast.SetComp))
        if isinstance(v, ast.Call):
            ctor = astutil.dotted_name(v.func).split(".")[-1]
            is_container = ctor in _CONTAINER_CTORS
        if not is_container:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def class_lock_attrs(cls: ast.ClassDef) -> dict[str, str]:
    """{attr: ctor} for attributes assigned from threading.Lock()/RLock()/
    Condition() anywhere in the class."""
    out: dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        ctor = astutil.dotted_name(node.value.func).split(".")[-1]
        if ctor in _LOCK_CTORS:
            for t in node.targets:
                if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
                    out[t.attr] = ctor
    return out


def module_lock_names(tree: ast.Module) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        ctor = astutil.dotted_name(node.value.func).split(".")[-1]
        if ctor in _LOCK_CTORS:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = ctor
    return out


@dataclasses.dataclass(frozen=True)
class Acquisition:
    lock: str
    held: tuple[str, ...]  # locks already held when this one is taken
    line: int


@dataclasses.dataclass(frozen=True)
class CallSite:
    callees: tuple[str, ...]
    held: tuple[str, ...]
    line: int
    self_call: bool  # receiver provably the same instance (`self.m()`)


@dataclasses.dataclass(frozen=True)
class Effect:
    obj: str                # "path::Class.attr" or "path::NAME" (mod global)
    kind: str               # "read" | "iterate" | "rebind" | "mutate"
    held: tuple[str, ...]   # locks held at the access
    line: int


# Builtins whose argument is ITERATED with interleaving bytecodes — a
# concurrent structural mutation can raise "changed size during
# iteration" or skip/duplicate elements. (list()/tuple()/dict() copies
# are single C calls and count as plain reads: GIL-atomic.)
_ITERATING_FUNCS = frozenset({
    "sorted", "sum", "any", "all", "max", "min", "enumerate", "zip",
    "map", "filter",
})


@dataclasses.dataclass
class FuncSummary:
    fid: str
    path: str
    cls: Optional[str]
    name: str
    entry_locks: tuple[str, ...]
    acquisitions: tuple[Acquisition, ...]
    calls: tuple[CallSite, ...]
    key_params_consumed: tuple[str, ...]
    donates: Optional[tuple[int, ...]]  # returned-callable donated positions
    effects: tuple[Effect, ...] = ()


class SummaryIndex:
    """All function summaries over a CallGraph's files, plus the
    may-acquire fixpoint."""

    def __init__(self, repo: Repo, graph: CallGraph):
        self.repo = repo
        self.graph = graph
        self.summaries: dict[str, FuncSummary] = {}
        self._class_locks: dict[tuple[str, str], dict[str, str]] = {}
        self._module_locks: dict[str, dict[str, str]] = {}
        # lock id -> threading ctor name ("Lock"/"RLock"/"Condition")
        self.lock_kinds: dict[str, str] = {}
        for (path, cname), cls in graph.classes.items():
            attrs = class_lock_attrs(cls)
            self._class_locks[(path, cname)] = attrs
            for attr, ctor in attrs.items():
                self.lock_kinds[f"{path}::{cname}.{attr}"] = ctor
        self._module_mutables: dict[str, set[str]] = {}
        for path in graph.paths:
            mlocks = module_lock_names(repo.tree(path))
            self._module_locks[path] = mlocks
            for name, ctor in mlocks.items():
                self.lock_kinds[f"{path}::{name}"] = ctor
            self._module_mutables[path] = module_mutables(repo.tree(path))
        # (parent fid, nested def name) -> synthetic fid, for thread-root
        # discovery (`threading.Thread(target=work)` where work is a
        # closure). Synthetic summaries live in self.summaries too.
        self.nested_defs: dict[tuple[str, str], str] = {}
        for fid, fd in list(graph.funcs.items()):
            self.summaries[fid] = self._summarize(fd)
        self._may_acquire: Optional[dict[str, set[str]]] = None
        self._may_raise: Optional[dict[str, bool]] = None

    # ---------------- per-function walk ---------------- #

    def _entry_locks(self, fd: FuncDef) -> tuple[str, ...]:
        """`*_locked` methods run with the class lock held BY CONVENTION —
        only claimable when the class has exactly one lock attr (ambiguous
        multi-lock classes get no assumption: missing edges over false
        ones)."""
        if fd.cls is None or not fd.name.endswith("_locked"):
            return ()
        locks = self._class_locks.get((fd.path, fd.cls), set())
        if len(locks) == 1:
            return (f"{fd.path}::{fd.cls}.{next(iter(locks))}",)
        return ()

    def _lock_id_for_with(self, fd: FuncDef, ctx: ast.expr,
                          me: Optional[str]) -> Optional[str]:
        if (isinstance(ctx, ast.Attribute) and isinstance(ctx.value, ast.Name)
                and me is not None and ctx.value.id == me and fd.cls):
            if ctx.attr in self._class_locks.get((fd.path, fd.cls), ()):
                return f"{fd.path}::{fd.cls}.{ctx.attr}"
            return None
        if isinstance(ctx, ast.Name):
            if ctx.id in self._module_locks.get(fd.path, ()):
                return f"{fd.path}::{ctx.id}"
        return None

    def _donated_positions(self, fn) -> Optional[tuple[int, ...]]:
        """Base donated positions of a returned jax.jit callable: the
        FIRST literal tuple bound to donate_argnums (or to the local it
        names). Conditional `donate += (...)` extensions are ignored —
        summaries only claim what is donated on EVERY path."""
        lit_tuples: dict[str, tuple[int, ...]] = {}
        jitted: dict[str, tuple[int, ...]] = {}
        returned: Optional[tuple[int, ...]] = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Tuple):
                vals = []
                ok = True
                for e in node.value.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        vals.append(e.value)
                    else:
                        ok = False
                for t in node.targets:
                    if ok and isinstance(t, ast.Name) and t.id not in lit_tuples:
                        lit_tuples[t.id] = tuple(vals)
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                call = node.value
                if astutil.dotted_name(call.func) in ("jax.jit", "jit"):
                    pos: Optional[tuple[int, ...]] = None
                    for kw in call.keywords:
                        if kw.arg != "donate_argnums":
                            continue
                        v = kw.value
                        if isinstance(v, ast.Tuple):
                            got = [e.value for e in v.elts
                                   if isinstance(e, ast.Constant)
                                   and isinstance(e.value, int)]
                            pos = tuple(got)
                        elif isinstance(v, ast.Constant) and isinstance(v.value, int):
                            pos = (v.value,)
                        elif isinstance(v, ast.Name) and v.id in lit_tuples:
                            pos = lit_tuples[v.id]
                    if pos:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                jitted[t.id] = pos
        for node in ast.walk(fn):
            if (isinstance(node, ast.Return) and isinstance(node.value, ast.Name)
                    and node.value.id in jitted):
                returned = jitted[node.value.id]
        return returned

    @staticmethod
    def _key_params(fn) -> set[str]:
        return {
            a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)
            + list(fn.args.kwonlyargs)
            if a.arg in KEY_PARAM_NAMES or a.arg.endswith("_key")
        }

    def _is_method_attr(self, path: str, cls: Optional[str],
                        attr: str) -> bool:
        """Loading a bound method (`self.m` in `self.m()`) is not a state
        read — filter those out of the effect set."""
        if cls is None:
            return False
        return self.graph.method_fid(path, cls, attr) is not None

    _UNSET = object()

    def _summarize(self, fd: FuncDef, me_override=_UNSET) -> FuncSummary:
        if me_override is not self._UNSET:
            me = me_override  # nested def: the enclosing receiver closes over
        else:
            me = astutil.self_name(fd.node) if fd.cls else None
        entry = self._entry_locks(fd)
        ltypes = dict(self.graph.local_types(fd.path, fd.node))
        if me_override is not self._UNSET and me is not None and fd.cls is not None:
            # Let `self.m()` resolve inside the closure: the free receiver
            # is typed as the enclosing class.
            ltypes.setdefault(me, set()).add((fd.path, fd.cls))
        acquisitions: list[Acquisition] = []
        calls: list[CallSite] = []
        effects: list[Effect] = []
        key_params = self._key_params(fd.node)
        keys_consumed: set[str] = set()
        has_jit = False
        nested: list = []
        globals_here = self._module_mutables.get(fd.path, set())
        # Names the function declares `global` (stores rebind the module
        # binding) vs names it shadows with a local assignment or param.
        gdecl: set[str] = set()
        shadowed: set[str] = set()
        a = fd.node.args
        for p in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
            shadowed.add(p.arg)
        for sub in ast.walk(fd.node):
            if isinstance(sub, ast.Global):
                gdecl |= set(sub.names)
            elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                shadowed.add(sub.id)
        shadowed -= gdecl
        # Attribute nodes already consumed by a write/mutate record — their
        # Load visit must not double-report a read.
        claimed: set[int] = set()

        def attr_obj(node: ast.AST) -> Optional[tuple[str, ast.AST]]:
            """(effect object id, the Attribute node) for `self.x` /
            `typed_local.x` receivers; None when the receiver is unknown."""
            if not isinstance(node, ast.Attribute):
                return None
            v = node.value
            if isinstance(v, ast.Name):
                if me is not None and v.id == me and fd.cls is not None:
                    return (f"{fd.path}::{fd.cls}.{node.attr}", node)
                cands = ltypes.get(v.id, ())
                if len(cands) == 1 and v.id != me:
                    (cp, cc), = cands
                    return (f"{cp}::{cc}.{node.attr}", node)
            return None

        def store_target(t: ast.AST, held: tuple[str, ...],
                         kind_for_attr: str) -> None:
            """Record effects for one assignment target (Tuple/Starred
            unpacked). kind_for_attr: 'rebind' for =, 'mutate' for +=."""
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    store_target(e, held, kind_for_attr)
                return
            if isinstance(t, ast.Starred):
                store_target(t.value, held, kind_for_attr)
                return
            if isinstance(t, ast.Attribute):
                got = attr_obj(t)
                if got is not None:
                    effects.append(Effect(got[0], kind_for_attr, held, t.lineno))
                    return
                # `self.cfg.field = v` — a field write THROUGH an attribute
                # is a mutation of that attribute's object.
                inner = attr_obj(t.value)
                if inner is not None:
                    claimed.add(id(inner[1]))
                    effects.append(Effect(inner[0], "mutate", held, t.lineno))
                return
            if isinstance(t, ast.Subscript):
                inner = attr_obj(t.value)
                if inner is not None:
                    claimed.add(id(inner[1]))
                    effects.append(Effect(inner[0], "mutate", held, t.lineno))
                elif (isinstance(t.value, ast.Name)
                      and t.value.id in globals_here
                      and t.value.id not in shadowed):
                    effects.append(Effect(f"{fd.path}::{t.value.id}", "mutate",
                                          held, t.lineno))
                return
            if isinstance(t, ast.Name) and t.id in gdecl and t.id in globals_here:
                effects.append(Effect(f"{fd.path}::{t.id}", "rebind",
                                      held, t.lineno))

        def mark_iterates(exprs, held: tuple[str, ...]) -> None:
            """Attr/global loads inside an iteration expression are
            'iterate' effects — the dangerous container read. Loads wrapped
            in an atomic copy (`for g in list(self.galleries)`,
            `sorted(list(self.events))`) iterate the COPY, not the shared
            object, and stay plain reads."""
            def nodes_outside_copies(expr):
                if (isinstance(expr, ast.Call)
                        and isinstance(expr.func, ast.Name)
                        and expr.func.id in ("list", "tuple", "set", "dict",
                                             "frozenset", "bytes",
                                             "bytearray")):
                    return
                yield expr
                for child in ast.iter_child_nodes(expr):
                    yield from nodes_outside_copies(child)

            for expr in exprs:
                if expr is None:
                    continue
                for sub in nodes_outside_copies(expr):
                    if (isinstance(sub, ast.Attribute)
                            and isinstance(sub.ctx, ast.Load)
                            and id(sub) not in claimed):
                        got = attr_obj(sub)
                        if got is not None:
                            claimed.add(id(sub))
                            effects.append(Effect(got[0], "iterate", held,
                                                  sub.lineno))
                    elif (isinstance(sub, ast.Name)
                          and isinstance(sub.ctx, ast.Load)
                          and sub.id in globals_here
                          and sub.id not in shadowed
                          and id(sub) not in claimed):
                        claimed.add(id(sub))
                        effects.append(Effect(f"{fd.path}::{sub.id}",
                                              "iterate", held, sub.lineno))

        def walk(node: ast.AST, held: tuple[str, ...]) -> None:
            nonlocal has_jit
            if isinstance(node, (ast.For, ast.AsyncFor)):
                mark_iterates([node.iter], held)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                mark_iterates([g.iter for g in node.generators], held)
            elif (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                  and node.func.id in _ITERATING_FUNCS):
                mark_iterates(node.args, held)
            if isinstance(node, ast.With):
                for item in node.items:
                    lock = self._lock_id_for_with(fd, item.context_expr, me)
                    if lock is not None:
                        acquisitions.append(Acquisition(lock, held, node.lineno))
                        held = held + (lock,)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    store_target(t, held, "rebind")
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                store_target(node.target, held, "rebind")
            elif isinstance(node, ast.AugAssign):
                store_target(node.target, held, "mutate")
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    store_target(t, held, "rebind" if isinstance(t, ast.Attribute)
                                 else "mutate")
            if isinstance(node, ast.Call):
                name = astutil.dotted_name(node.func)
                if name in ("jax.jit", "jit"):
                    has_jit = True
                if (key_params and name.startswith("jax.random.")
                        and name.split(".")[-1] in KEY_CONSUMERS):
                    for arg in node.args:
                        for sub in ast.walk(arg):
                            if isinstance(sub, ast.Name) and sub.id in key_params:
                                keys_consumed.add(sub.id)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in MUTATOR_METHODS):
                    recv = node.func.value
                    got = attr_obj(recv)
                    if got is not None:
                        claimed.add(id(got[1]))
                        effects.append(Effect(got[0], "mutate", held,
                                              node.lineno))
                    elif (isinstance(recv, ast.Subscript)):
                        inner = attr_obj(recv.value)
                        if inner is not None:
                            claimed.add(id(inner[1]))
                            effects.append(Effect(inner[0], "mutate", held,
                                                  node.lineno))
                    elif (isinstance(recv, ast.Name) and recv.id in globals_here
                          and recv.id not in shadowed):
                        effects.append(Effect(f"{fd.path}::{recv.id}", "mutate",
                                              held, node.lineno))
                cands = self.graph.resolve(fd, node, local_types=ltypes)
                is_self = (
                    isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and me is not None and node.func.value.id == me
                )
                if cands:
                    calls.append(CallSite(cands, held, node.lineno, is_self))
            if (isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load)
                    and id(node) not in claimed):
                got = attr_obj(node)
                if got is not None:
                    obj = got[0]
                    _, _, qual = obj.partition("::")
                    ocls, _, oattr = qual.rpartition(".")
                    opath = obj.partition("::")[0]
                    if not self._is_method_attr(opath, ocls or None, oattr):
                        effects.append(Effect(obj, "read", held, node.lineno))
            if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                    and node.id in globals_here and node.id not in shadowed
                    and id(node) not in claimed):
                effects.append(Effect(f"{fd.path}::{node.id}", "read", held,
                                      node.lineno))
            for child in ast.iter_child_nodes(node):
                # Nested defs execute later, not here — their bodies become
                # SYNTHETIC summaries below (a `with lock:` wrapping a def
                # does NOT mean the def runs locked, so their held-set
                # starts empty). The jit scan still covers them inline: a
                # builder's nested jitted fn is the whole point of the
                # donation summary.
                if isinstance(child, astutil.FunctionNode) and child is not fd.node:
                    nested.append(child)
                    for sub in ast.walk(child):
                        if (isinstance(sub, ast.Call)
                                and astutil.dotted_name(sub.func)
                                in ("jax.jit", "jit")):
                            has_jit = True
                            break
                    continue
                walk(child, held)

        walk(fd.node, entry)
        for child in nested:
            nfid = f"{fd.fid}.{child.name}@{child.lineno}"
            nfd = FuncDef(nfid, fd.path, fd.cls, child.name, child)
            self.nested_defs[(fd.fid, child.name)] = nfid
            self.summaries[nfid] = self._summarize(nfd, me_override=me)
        return FuncSummary(
            fid=fd.fid, path=fd.path, cls=fd.cls, name=fd.name,
            entry_locks=entry,
            acquisitions=tuple(acquisitions),
            calls=tuple(calls),
            key_params_consumed=tuple(sorted(keys_consumed)),
            donates=self._donated_positions(fd.node) if has_jit else None,
            effects=tuple(effects),
        )

    # ---------------- fixpoint ---------------- #

    def may_acquire(self) -> dict[str, set[str]]:
        """fid -> every lock the function may take during its execution,
        transitively through resolved calls, propagated to a fixpoint."""
        if self._may_acquire is not None:
            return self._may_acquire
        acq = {
            fid: {a.lock for a in s.acquisitions}
            for fid, s in self.summaries.items()
        }
        changed = True
        while changed:
            changed = False
            for fid, s in self.summaries.items():
                cur = acq[fid]
                for site in s.calls:
                    for callee in site.callees:
                        extra = acq.get(callee)
                        if extra and not extra <= cur:
                            cur |= extra
                            changed = True
        self._may_acquire = acq
        return acq

    def may_raise(self) -> dict[str, bool]:
        """fid -> may an exception ESCAPE a call to this function. Seeded
        by explicit non-assert `raise` statements and KNOWN_RAISERS calls,
        propagated up the call graph like may_acquire — but an except-all
        barrier around a call site cuts the edge: `try: x() except
        Exception: ...` absorbs whatever x may raise (ISSUE 20). The
        exception-edge CFG consumes this to decide which out-of-try calls
        get a raise edge."""
        if self._may_raise is not None:
            return self._may_raise
        seeds: dict[str, bool] = {}
        escaping: dict[str, Optional[frozenset[int]]] = {}
        for fid, s in self.summaries.items():
            fd = self.graph.funcs.get(fid)
            if fd is not None:
                seeds[fid], escaping[fid] = escape_info(fd.node)
            else:
                # Nested defs: no barrier map — treat every call line as
                # escaping (conservative) and no direct seed.
                seeds[fid], escaping[fid] = False, None
        out = dict(seeds)
        changed = True
        while changed:
            changed = False
            for fid, s in self.summaries.items():
                if out[fid]:
                    continue
                esc = escaping[fid]
                for site in s.calls:
                    if esc is not None and site.line not in esc:
                        continue
                    if any(out.get(c) for c in site.callees):
                        out[fid] = True
                        changed = True
                        break
        self._may_raise = out
        return out


def summaries_for(repo: Repo, globs: tuple[str, ...]) -> SummaryIndex:
    """Repo-cached SummaryIndex per glob set — the per-function summary
    cache that rides alongside the AST/module cache."""
    cache = getattr(repo, "_summary_indexes", None)
    if cache is None:
        cache = repo._summary_indexes = {}
    key = tuple(sorted(globs))
    if key not in cache:
        cache[key] = SummaryIndex(repo, callgraph_for(repo, key))
    return cache[key]
