"""Per-function summaries for interprocedural passes (ISSUE 8).

One walk per function computes everything the new passes consume, cached on
the Repo next to the AST/module cache so the four passes (and a --since
rerun) share a single build:

  - locks:     which locks a function ACQUIRES (`with self.lock:` /
               `with MODULE_LOCK:`), which locks are held AT each
               acquisition and at each call site, and which locks a
               `*_locked` method assumes held on entry (the repo convention:
               caller holds the class lock). Lock identity is
               "path::Class.attr" (or "path::NAME" for module locks) — one
               id per lock OBJECT SLOT, which is the granularity deadlock
               ordering is about.
  - calls:     resolved candidate callees (tools.lint.callgraph) with the
               held-lock set, for the lock-order fixpoint.
  - rng keys:  whether a key-named parameter is consumed (passed to a
               jax.random sampler or split/fold_in) — callers treat passing
               a key to such a helper as one consumption of that key.
  - donation:  whether the function returns a `jax.jit(..., donate_argnums=...)`
               callable and which positions are ALWAYS donated (the literal
               base tuple; conditional extensions are not claimed).

The fixpoint (`may_acquire`) propagates lock acquisition up the call graph
until stable, which is what turns "this function takes a lock" into "this
call may take that lock while you hold yours" — the lock-order edge.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from . import astutil
from .callgraph import CallGraph, FuncDef, callgraph_for
from .core import Repo

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

# The union of every interprocedural pass's default targets: passes running
# with DEFAULT scope share ONE SummaryIndex build under this key instead of
# each building their own (fixture runs with custom globs still get their
# own small index).
DEFAULT_SUMMARY_GLOBS = (
    "localai_tpu/engine/*.py",
    "localai_tpu/server/manager.py",
    "localai_tpu/federation/router.py",
    "localai_tpu/cluster/*.py",
    "localai_tpu/models/*.py",
    "localai_tpu/ops/*.py",
    "localai_tpu/parallel/*.py",
    "localai_tpu/train/*.py",
)

# jax.random functions that CONSUME a key. `split` is a consumer (splitting
# the same key twice yields the same children — the canonical correlated-
# streams bug); `fold_in` is NOT (fold_in(key, i) with varying data is the
# blessed way to derive many independent keys from one base).
KEY_CONSUMERS = {
    "normal", "uniform", "categorical", "gumbel", "bernoulli", "randint",
    "truncated_normal", "permutation", "choice", "exponential", "laplace",
    "gamma", "beta", "dirichlet", "poisson", "rademacher", "bits",
    "split",
}
KEY_PARAM_NAMES = {"key", "rng", "rngs", "prng_key", "base_key"}


def class_lock_attrs(cls: ast.ClassDef) -> dict[str, str]:
    """{attr: ctor} for attributes assigned from threading.Lock()/RLock()/
    Condition() anywhere in the class."""
    out: dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        ctor = astutil.dotted_name(node.value.func).split(".")[-1]
        if ctor in _LOCK_CTORS:
            for t in node.targets:
                if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
                    out[t.attr] = ctor
    return out


def module_lock_names(tree: ast.Module) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        ctor = astutil.dotted_name(node.value.func).split(".")[-1]
        if ctor in _LOCK_CTORS:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = ctor
    return out


@dataclasses.dataclass(frozen=True)
class Acquisition:
    lock: str
    held: tuple[str, ...]  # locks already held when this one is taken
    line: int


@dataclasses.dataclass(frozen=True)
class CallSite:
    callees: tuple[str, ...]
    held: tuple[str, ...]
    line: int
    self_call: bool  # receiver provably the same instance (`self.m()`)


@dataclasses.dataclass
class FuncSummary:
    fid: str
    path: str
    cls: Optional[str]
    name: str
    entry_locks: tuple[str, ...]
    acquisitions: tuple[Acquisition, ...]
    calls: tuple[CallSite, ...]
    key_params_consumed: tuple[str, ...]
    donates: Optional[tuple[int, ...]]  # returned-callable donated positions


class SummaryIndex:
    """All function summaries over a CallGraph's files, plus the
    may-acquire fixpoint."""

    def __init__(self, repo: Repo, graph: CallGraph):
        self.repo = repo
        self.graph = graph
        self.summaries: dict[str, FuncSummary] = {}
        self._class_locks: dict[tuple[str, str], dict[str, str]] = {}
        self._module_locks: dict[str, dict[str, str]] = {}
        # lock id -> threading ctor name ("Lock"/"RLock"/"Condition")
        self.lock_kinds: dict[str, str] = {}
        for (path, cname), cls in graph.classes.items():
            attrs = class_lock_attrs(cls)
            self._class_locks[(path, cname)] = attrs
            for attr, ctor in attrs.items():
                self.lock_kinds[f"{path}::{cname}.{attr}"] = ctor
        for path in graph.paths:
            mlocks = module_lock_names(repo.tree(path))
            self._module_locks[path] = mlocks
            for name, ctor in mlocks.items():
                self.lock_kinds[f"{path}::{name}"] = ctor
        for fid, fd in graph.funcs.items():
            self.summaries[fid] = self._summarize(fd)
        self._may_acquire: Optional[dict[str, set[str]]] = None

    # ---------------- per-function walk ---------------- #

    def _entry_locks(self, fd: FuncDef) -> tuple[str, ...]:
        """`*_locked` methods run with the class lock held BY CONVENTION —
        only claimable when the class has exactly one lock attr (ambiguous
        multi-lock classes get no assumption: missing edges over false
        ones)."""
        if fd.cls is None or not fd.name.endswith("_locked"):
            return ()
        locks = self._class_locks.get((fd.path, fd.cls), set())
        if len(locks) == 1:
            return (f"{fd.path}::{fd.cls}.{next(iter(locks))}",)
        return ()

    def _lock_id_for_with(self, fd: FuncDef, ctx: ast.expr,
                          me: Optional[str]) -> Optional[str]:
        if (isinstance(ctx, ast.Attribute) and isinstance(ctx.value, ast.Name)
                and me is not None and ctx.value.id == me and fd.cls):
            if ctx.attr in self._class_locks.get((fd.path, fd.cls), ()):
                return f"{fd.path}::{fd.cls}.{ctx.attr}"
            return None
        if isinstance(ctx, ast.Name):
            if ctx.id in self._module_locks.get(fd.path, ()):
                return f"{fd.path}::{ctx.id}"
        return None

    def _donated_positions(self, fn) -> Optional[tuple[int, ...]]:
        """Base donated positions of a returned jax.jit callable: the
        FIRST literal tuple bound to donate_argnums (or to the local it
        names). Conditional `donate += (...)` extensions are ignored —
        summaries only claim what is donated on EVERY path."""
        lit_tuples: dict[str, tuple[int, ...]] = {}
        jitted: dict[str, tuple[int, ...]] = {}
        returned: Optional[tuple[int, ...]] = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Tuple):
                vals = []
                ok = True
                for e in node.value.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        vals.append(e.value)
                    else:
                        ok = False
                for t in node.targets:
                    if ok and isinstance(t, ast.Name) and t.id not in lit_tuples:
                        lit_tuples[t.id] = tuple(vals)
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                call = node.value
                if astutil.dotted_name(call.func) in ("jax.jit", "jit"):
                    pos: Optional[tuple[int, ...]] = None
                    for kw in call.keywords:
                        if kw.arg != "donate_argnums":
                            continue
                        v = kw.value
                        if isinstance(v, ast.Tuple):
                            got = [e.value for e in v.elts
                                   if isinstance(e, ast.Constant)
                                   and isinstance(e.value, int)]
                            pos = tuple(got)
                        elif isinstance(v, ast.Constant) and isinstance(v.value, int):
                            pos = (v.value,)
                        elif isinstance(v, ast.Name) and v.id in lit_tuples:
                            pos = lit_tuples[v.id]
                    if pos:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                jitted[t.id] = pos
        for node in ast.walk(fn):
            if (isinstance(node, ast.Return) and isinstance(node.value, ast.Name)
                    and node.value.id in jitted):
                returned = jitted[node.value.id]
        return returned

    @staticmethod
    def _key_params(fn) -> set[str]:
        return {
            a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)
            + list(fn.args.kwonlyargs)
            if a.arg in KEY_PARAM_NAMES or a.arg.endswith("_key")
        }

    def _summarize(self, fd: FuncDef) -> FuncSummary:
        me = astutil.self_name(fd.node) if fd.cls else None
        entry = self._entry_locks(fd)
        ltypes = self.graph.local_types(fd.path, fd.node)
        acquisitions: list[Acquisition] = []
        calls: list[CallSite] = []
        key_params = self._key_params(fd.node)
        keys_consumed: set[str] = set()
        has_jit = False

        def walk(node: ast.AST, held: tuple[str, ...]) -> None:
            nonlocal has_jit
            if isinstance(node, ast.With):
                for item in node.items:
                    lock = self._lock_id_for_with(fd, item.context_expr, me)
                    if lock is not None:
                        acquisitions.append(Acquisition(lock, held, node.lineno))
                        held = held + (lock,)
            if isinstance(node, ast.Call):
                name = astutil.dotted_name(node.func)
                if name in ("jax.jit", "jit"):
                    has_jit = True
                if (key_params and name.startswith("jax.random.")
                        and name.split(".")[-1] in KEY_CONSUMERS):
                    for a in node.args:
                        for sub in ast.walk(a):
                            if isinstance(sub, ast.Name) and sub.id in key_params:
                                keys_consumed.add(sub.id)
                cands = self.graph.resolve(fd, node, local_types=ltypes)
                is_self = (
                    isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and me is not None and node.func.value.id == me
                )
                if cands:
                    calls.append(CallSite(cands, held, node.lineno, is_self))
            for child in ast.iter_child_nodes(node):
                # Nested defs execute later, not here — their bodies are
                # summarized separately (and a `with lock:` wrapping a def
                # does NOT mean the def runs locked). The jit/key scans DO
                # cover nested defs: a builder's nested jitted fn is the
                # whole point of the donation summary.
                if isinstance(child, astutil.FunctionNode) and child is not fd.node:
                    for sub in ast.walk(child):
                        if (isinstance(sub, ast.Call)
                                and astutil.dotted_name(sub.func)
                                in ("jax.jit", "jit")):
                            has_jit = True
                            break
                    continue
                walk(child, held)

        walk(fd.node, entry)
        return FuncSummary(
            fid=fd.fid, path=fd.path, cls=fd.cls, name=fd.name,
            entry_locks=entry,
            acquisitions=tuple(acquisitions),
            calls=tuple(calls),
            key_params_consumed=tuple(sorted(keys_consumed)),
            donates=self._donated_positions(fd.node) if has_jit else None,
        )

    # ---------------- fixpoint ---------------- #

    def may_acquire(self) -> dict[str, set[str]]:
        """fid -> every lock the function may take during its execution,
        transitively through resolved calls, propagated to a fixpoint."""
        if self._may_acquire is not None:
            return self._may_acquire
        acq = {
            fid: {a.lock for a in s.acquisitions}
            for fid, s in self.summaries.items()
        }
        changed = True
        while changed:
            changed = False
            for fid, s in self.summaries.items():
                cur = acq[fid]
                for site in s.calls:
                    for callee in site.callees:
                        extra = acq.get(callee)
                        if extra and not extra <= cur:
                            cur |= extra
                            changed = True
        self._may_acquire = acq
        return acq


def summaries_for(repo: Repo, globs: tuple[str, ...]) -> SummaryIndex:
    """Repo-cached SummaryIndex per glob set — the per-function summary
    cache that rides alongside the AST/module cache."""
    cache = getattr(repo, "_summary_indexes", None)
    if cache is None:
        cache = repo._summary_indexes = {}
    key = tuple(sorted(globs))
    if key not in cache:
        cache[key] = SummaryIndex(repo, callgraph_for(repo, key))
    return cache[key]
