"""Thread model for localai-lint (ISSUE 15): thread-root discovery,
per-root reachability, and `# thread:` declarations.

The serving core is a dozen cooperating thread roles — engine loop,
journal drainer, watchdog, config watcher, cluster pumps, HTTP handler
threads, stream readers, federation prober — sharing mutable engine /
manager / metrics state. PR 11 hand-fixed a data race in
`Metrics._gauge_sources` (add_gauge_source vs /metrics render), and the
EventJournal's lock-free loop-thread append is safe only by an ownership
convention nothing checked. This module makes the thread structure itself
a lint-visible artifact:

- **Roots**: every `threading.Thread(target=...)` site project-wide is a
  root (role = the thread's `name=` pattern, `cluster-pump-{rid}` →
  `cluster-pump-*`); HTTP handler methods (router registrations in
  `server/`, `BaseHTTPRequestHandler` subclasses in `federation/` /
  `explorer/` — nested classes included) form one multi-instance
  `http-handler` root; and everything else a library user may call lands
  in the `main` root (all public functions/methods not owned by another
  root).
- **Reachability**: per-root reachable function sets over the
  interprocedural call graph (tools.lint.callgraph + summaries) — the
  attribution that turns a per-function attribute effect set into "root A
  writes this, root B reads it".
- **Declarations**: `# thread: <role>-only` on a `def` makes single-owner
  code explicit (EventJournal.append, slot-table mutators); `# thread:
  single-writer <role>` on an `__init__` attribute assignment blesses a
  deliberately lock-free single-writer/best-effort-reader slot (the
  journal ring). Both are *checked*: the thread-affinity pass reports
  declared functions reachable from foreign roots and stale roles; the
  shared-state-race pass reports writes to a single-writer slot from any
  other role.

The conftest thread-leak guard and this discovery share ONE source:
`GUARDED_THREAD_PREFIXES` below is imported by tests/conftest.py, and a
drift test in tests/test_lint.py fails when a discovered Thread site is
covered by neither the guard list nor `UNGUARDED_THREAD_ROLES` (each
exemption carries a written reason, suppression-style).
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import re
from typing import Optional

from . import astutil
from .core import Repo
from .summaries import DEFAULT_SUMMARY_GLOBS, SummaryIndex, summaries_for

# ---------------------------------------------------------------------- #
# The conftest thread-leak guard's watch list (single source, ISSUE 15):
# threads with these name prefixes must be GONE after each test module.
# ---------------------------------------------------------------------- #
GUARDED_THREAD_PREFIXES = (
    "engine-loop",
    "engine-drain",
    "watchdog",
    "config-watcher",
    "stream-reader",
    "fed-health",
    # Cluster scheduler threads (ISSUE 8 satellite): the per-request
    # dispatch pumps ("cluster-pump-<rid>") own the reroute path AND the
    # scheduler's gauge refresh (refresh() runs inline on them). A pump
    # that outlives its request means a terminal event was never posted
    # (the ClusterClient _finish/_abort contract) and the thread spins on
    # a dead handle forever. "cluster-gauge" guards any future dedicated
    # refresher thread.
    "cluster-pump",
    "cluster-gauge",
)

# Thread roles discovery knows about that the leak guard deliberately does
# NOT watch. Every entry needs a written reason — the drift test fails on
# a role covered by neither list. Patterns are fnmatch'd against the
# discovered role.
UNGUARDED_THREAD_ROLES = {
    "prefix-admit-compile": "one-shot AOT compile worker; exits after "
                            "publishing (or failing) its executable",
    "grammar-dfa-build": "one-shot DFA table build; exits after caching",
    "model-teardown": "one-shot crash-eviction teardown; exits after "
                      "freeing the dead engine",
    "span-import": "one-shot span-transfer merge worker; exits after the "
                   "import settles (done Event)",
    "fed-server": "ThreadingHTTPServer acceptor; lives for the router's "
                  "lifetime, stopped by server.shutdown() in stop()",
    "explorer-server": "ThreadingHTTPServer acceptor for the explorer UI; "
                       "stopped by server.shutdown()",
    "explorer-discovery": "explorer poller with its own stop() Event; "
                          "holds HTTP handles only, never engine state",
    "gallery-install": "daemon job worker parked on its queue between "
                       "installs; holds no engine/device handles",
    "agent-jobs": "scheduler loop with its own stop() Event, joined in "
                  "stop(); no engine handles held between ticks",
    "multihost-drain": "pipe drain for a child worker process; exits when "
                       "the child's stdout closes",
    "models-import": "one-shot model-import job worker (models_api); "
                     "terminal state recorded on the job dict",
    "unload-drain": "one-shot drain-then-teardown worker for an explicit "
                    "unload; exits after drain_s at the latest",
}

# Matches `# thread: <role>-only` (function affinity declaration).
_AFFINITY_RE = re.compile(r"#\s*thread:\s*(?P<role>[a-z0-9_*-]+?)-only\b")
# Matches `# thread: single-writer <role>` (attribute declaration).
_SINGLE_WRITER_RE = re.compile(
    r"#\s*thread:\s*single-writer\s+(?P<role>[a-z0-9_*-]+)"
)
# Matches `# thread: instance-owned <why>` (attribute declaration): each
# INSTANCE is owned/serialized by exactly one thread at a time (per-request
# objects, ownership handed over by a pop/queue). Class-level sharing
# analysis cannot see instance boundaries, so the owner states them.
_INSTANCE_OWNED_RE = re.compile(r"#\s*thread:\s*instance-owned\b")

_HTTP_VERBS = {"GET", "POST", "PUT", "DELETE", "PATCH", "HEAD", "OPTIONS"}


@dataclasses.dataclass
class ThreadSite:
    """One `threading.Thread(...)` construction site."""
    path: str
    line: int
    role: str                 # canonical role ("engine-loop", "models-import")
    pattern: str              # thread-name pattern ("cluster-pump-*"); ""
    #                           when the site passes no name= kwarg
    target_fid: Optional[str]  # resolved entry, None for lambda/unknown
    multi: bool               # several live instances possible
    in_summary: str           # fid of the function containing the site


@dataclasses.dataclass
class ThreadRoot:
    role: str
    kind: str                  # "thread" | "http" | "main"
    entries: tuple[str, ...]   # entry fids
    multi: bool
    path: str = ""
    line: int = 0
    pattern: str = ""


def role_matches(declared: str, root: "ThreadRoot") -> bool:
    """Does a declared role name cover a discovered root? Exact role,
    fnmatch against the role, or fnmatch against the thread-name pattern
    (`cluster-pump` covers `cluster-pump-*`)."""
    if declared == root.role:
        return True
    if fnmatch.fnmatch(root.role, declared) or fnmatch.fnmatch(
            root.role, declared + "-*"):
        return True
    if root.pattern and (fnmatch.fnmatch(root.pattern, declared)
                         or fnmatch.fnmatch(root.pattern, declared + "-*")):
        return True
    return False


def _name_pattern(kw: Optional[ast.expr]) -> tuple[str, str, bool]:
    """(role, pattern, multi_hint) from a Thread name= kwarg value.
    f-strings become fnmatch patterns: f"cluster-pump-{rid}" ->
    ("cluster-pump-*", multi)."""
    if isinstance(kw, ast.Constant) and isinstance(kw.value, str):
        return kw.value, kw.value, False
    if isinstance(kw, ast.JoinedStr):
        parts = []
        for v in kw.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        pat = "".join(parts)
        role = pat.rstrip("*-")
        return (role or pat), pat, True
    return "", "", False


class ThreadModel:
    """Roots + per-root reachability + declarations over one
    SummaryIndex. Cached on the Repo via threads_for()."""

    def __init__(self, repo: Repo, idx: SummaryIndex):
        self.repo = repo
        self.idx = idx
        self.graph = idx.graph
        self.sites: list[ThreadSite] = []
        self.roots: list[ThreadRoot] = []
        # fid -> (declared role, path, line)
        self.affinity: dict[str, tuple[str, str, int]] = {}
        # attr obj id -> (declared role, path, line)
        self.single_writer: dict[str, tuple[str, str, int]] = {}
        # attr obj ids declared `# thread: instance-owned`
        self.instance_owned: set[str] = set()
        self._reach: dict[str, frozenset] = {}
        self._discover_sites()
        self._collect_declarations()
        self._build_roots()

    # ---------------- discovery ---------------- #

    def _thread_calls(self, fn: ast.AST):
        """(call, assigned_to_attr) for threading.Thread(...) ctor calls in
        a function body (nested defs included — sites inside closures still
        spawn threads)."""
        assigned: set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if any(isinstance(t, ast.Attribute) for t in node.targets):
                    assigned.add(id(node.value))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.dotted_name(node.func)
            if name in ("threading.Thread", "Thread"):
                yield node, id(node) in assigned

    def _resolve_target(self, fid: str, fd, call: ast.Call) -> tuple[
            Optional[str], bool]:
        """(target fid, is_serve_forever) for a Thread site's target=."""
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None:
            return None, False
        me = astutil.self_name(fd.node) if fd.cls else None
        if isinstance(target, ast.Attribute):
            if target.attr == "serve_forever":
                return None, True
            dn = astutil.dotted_name(target)
            parts = dn.split(".") if dn else []
            if me is not None and len(parts) == 2 and parts[0] == me:
                got = self.graph.method_fid(fd.path, fd.cls, target.attr)
                return got, False
            ltypes = self.graph.local_types(fd.path, fd.node)
            if len(parts) == 2 and parts[0] in ltypes:
                for (cp, cc) in ltypes[parts[0]]:
                    got = self.graph.method_fid(cp, cc, target.attr)
                    if got:
                        return got, False
            return None, False
        if isinstance(target, ast.Name):
            nested = self.idx.nested_defs.get((fid, target.id))
            if nested:
                return nested, False
            ent = self.graph._module_names.get(fd.path, {}).get(target.id)
            if ent and ent[0] == "func":
                return ent[1], False
        return None, False

    def _discover_sites(self) -> None:
        for fid, fd in self.graph.funcs.items():
            for call, assigned in self._thread_calls(fd.node):
                name_kw = None
                for kw in call.keywords:
                    if kw.arg == "name":
                        name_kw = kw.value
                role, pattern, multi_hint = _name_pattern(name_kw)
                tfid, is_serve = self._resolve_target(fid, fd, call)
                if is_serve and not role:
                    stem = fd.path.rsplit("/", 1)[-1][:-3]
                    role = f"{stem}-server"
                if not role:
                    # Unnamed thread: derive a stable role from the target.
                    tname = tfid.rsplit(".", 1)[-1].split("@")[0] if tfid \
                        else "<lambda>"
                    stem = fd.path.rsplit("/", 1)[-1][:-3]
                    role = f"{stem}:{tname}"
                self.sites.append(ThreadSite(
                    path=fd.path, line=call.lineno, role=role,
                    pattern=pattern, target_fid=tfid,
                    multi=multi_hint or not assigned,
                    in_summary=fid,
                ))

    def _handler_classes(self) -> list[tuple[str, str]]:
        """(path, class) of BaseHTTPRequestHandler subclasses (nested
        classes included — the call graph indexes them)."""
        out = []
        for (path, cname), node in self.graph.classes.items():
            bases = self.graph._bases.get((path, cname), [])
            if any("BaseHTTPRequestHandler" in b for b in bases):
                out.append((path, cname))
        return out

    def _http_entries(self) -> set[str]:
        entries: set[str] = set()
        # (a) router registrations: X.add("VERB", pattern, handler).
        for fid, fd in self.graph.funcs.items():
            ltypes = None
            for node in ast.walk(fd.node):
                if isinstance(node, astutil.FunctionNode) and node is not fd.node:
                    continue
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "add"
                        and len(node.args) >= 3
                        and isinstance(node.args[0], ast.Constant)
                        and str(node.args[0].value).upper() in _HTTP_VERBS):
                    continue
                handler = node.args[2]
                me = astutil.self_name(fd.node) if fd.cls else None
                if (isinstance(handler, ast.Attribute)
                        and isinstance(handler.value, ast.Name)
                        and me is not None and handler.value.id == me):
                    got = self.graph.method_fid(fd.path, fd.cls, handler.attr)
                    if got:
                        entries.add(got)
                elif isinstance(handler, ast.Name):
                    ent = self.graph._module_names.get(fd.path, {}).get(
                        handler.id)
                    if ent and ent[0] == "func":
                        entries.add(ent[1])
                elif isinstance(handler, ast.Lambda):
                    # `lambda req: ...m.render()...` — resolve the calls the
                    # lambda body makes with the enclosing context.
                    if ltypes is None:
                        ltypes = self.graph.local_types(fd.path, fd.node)
                    for sub in ast.walk(handler.body):
                        if isinstance(sub, ast.Call):
                            for cand in self.graph.resolve(
                                    fd, sub, local_types=ltypes):
                                entries.add(cand)
        # (b) every method of a BaseHTTPRequestHandler subclass.
        for (path, cname) in self._handler_classes():
            for mname, mfid in self.graph._methods.get((path, cname),
                                                       {}).items():
                entries.add(mfid)
        # (c) closure dispatch: a handler class nested inside a method of
        # an outer class calls the outer instance through a closure var the
        # resolver cannot type — the outer class's public methods ARE the
        # HTTP surface (FederationRouter.route, ExplorerServer handlers).
        handler_nodes = {id(self.graph.classes[k]): k
                         for k in self._handler_classes()}
        for (path, cname), node in list(self.graph.classes.items()):
            if (path, cname) in self._handler_classes():
                continue
            owns = False
            for sub in ast.walk(node):
                if id(sub) in handler_nodes and sub is not node:
                    owns = True
            if not owns:
                continue
            for mname, mfid in self.graph._methods.get((path, cname),
                                                       {}).items():
                if not mname.startswith("_"):
                    entries.add(mfid)
        return entries

    # ---------------- declarations ---------------- #

    def _collect_declarations(self) -> None:
        for fid, fd in self.graph.funcs.items():
            lines = self.repo.lines(fd.path)
            ln = fd.node.lineno
            texts = []
            if 1 <= ln <= len(lines):
                texts.append((lines[ln - 1], ln))
            if ln >= 2:
                texts.append((lines[ln - 2], ln - 1))
            for text, at in texts:
                m = _AFFINITY_RE.search(text)
                if m:
                    self.affinity[fid] = (m.group("role"), fd.path, at)
                    break
        # Attribute single-writer declarations: on `self.x = ...` lines
        # anywhere in a class body (construction is where they belong, but
        # the comment governs the slot wherever it sits).
        for (path, cname), node in self.graph.classes.items():
            lines = self.repo.lines(path)
            for sub in ast.walk(node):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                attrs = [t.attr for t in targets
                         if isinstance(t, ast.Attribute)
                         and isinstance(t.value, ast.Name)]
                if not attrs:
                    continue
                # The marker may sit on the assignment line or anywhere in
                # the comment BLOCK directly above it (declarations carry
                # written reasons, which wrap).
                candidates = [(lines[sub.lineno - 1], sub.lineno)]
                ln = sub.lineno - 1
                while ln >= 1 and lines[ln - 1].lstrip().startswith("#"):
                    candidates.append((lines[ln - 1], ln))
                    ln -= 1
                for text, at in candidates:
                    m = _SINGLE_WRITER_RE.search(text)
                    if m:
                        for attr in attrs:
                            self.single_writer[f"{path}::{cname}.{attr}"] = (
                                m.group("role"), path, at)
                        break
                    if _INSTANCE_OWNED_RE.search(text):
                        for attr in attrs:
                            self.instance_owned.add(f"{path}::{cname}.{attr}")
                        break

    # ---------------- roots ---------------- #

    def _build_roots(self) -> None:
        by_role: dict[str, ThreadRoot] = {}
        thread_targets: set[str] = set()
        for s in self.sites:
            if s.target_fid is None:
                continue
            thread_targets.add(s.target_fid)
            prev = by_role.get(s.role)
            if prev is None:
                by_role[s.role] = ThreadRoot(
                    role=s.role, kind="thread", entries=(s.target_fid,),
                    multi=s.multi, path=s.path, line=s.line,
                    pattern=s.pattern or s.role,
                )
            else:
                ents = tuple(sorted(set(prev.entries) | {s.target_fid}))
                prev.entries = ents
                prev.multi = prev.multi or s.multi
        http = self._http_entries()
        if http:
            by_role["http-handler"] = ThreadRoot(
                role="http-handler", kind="http",
                entries=tuple(sorted(http)), multi=True,
            )
        # Everything else a user may call from their own thread: public
        # functions and methods not owned by another root and not declared
        # `# thread: <role>-only` (the declaration is exactly the statement
        # that the main thread must NOT call it).
        owned = thread_targets | http | set(self.affinity)
        main_entries = []
        for fid, fd in self.graph.funcs.items():
            if fid in owned or fd.name.startswith("_"):
                continue
            main_entries.append(fid)
        by_role["main"] = ThreadRoot(
            role="main", kind="main", entries=tuple(sorted(main_entries)),
            multi=False,
        )
        self.roots = [by_role[r] for r in sorted(by_role)]

    # ---------------- reachability ---------------- #

    def reach(self, root: ThreadRoot) -> frozenset:
        """Fids reachable from a root's entries through resolved calls."""
        got = self._reach.get(root.role)
        if got is not None:
            return got
        seen: set[str] = set()
        frontier = [f for f in root.entries if f in self.idx.summaries]
        while frontier:
            fid = frontier.pop()
            if fid in seen:
                continue
            seen.add(fid)
            s = self.idx.summaries.get(fid)
            if s is None:
                continue
            for site in s.calls:
                for callee in site.callees:
                    if callee not in seen:
                        frontier.append(callee)
        out = frozenset(seen)
        self._reach[root.role] = out
        return out

    def roots_reaching(self, fid: str) -> list[ThreadRoot]:
        return [r for r in self.roots if fid in self.reach(r)]

    # ---------------- drift-test surface ---------------- #

    def discovered_roles(self) -> list[ThreadSite]:
        """Every Thread construction site (lambda targets included) — the
        conftest-guard drift test walks this."""
        return list(self.sites)


def threads_for(repo: Repo, globs: tuple[str, ...] = DEFAULT_SUMMARY_GLOBS
                ) -> ThreadModel:
    """Repo-cached ThreadModel per glob set, riding the same SummaryIndex
    the other interprocedural passes share. Like those summaries, the
    model is always built over the FULL glob set — --since must not narrow
    a cross-file invariant."""
    cache = getattr(repo, "_thread_models", None)
    if cache is None:
        cache = repo._thread_models = {}
    key = tuple(sorted(globs))
    if key not in cache:
        cache[key] = ThreadModel(repo, summaries_for(repo, key))
    return cache[key]
