"""Produce assets/vad-base.safetensors — the shipped VAD artifact.

Recipe (r5): formant-synthesis corpus (audio/formant_speech.py) + real
recorded backgrounds/negatives from the image's pygame example clips
(learned_vad.real_noise_clips). Run from the repo root:

    python tools/train_vad.py [steps]

Prints held-out synthetic metrics and the real-audio frame-FP rate; only
overwrite the asset when both look good (synthetic F1 >= 0.93, real FP
<= 0.05).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from localai_tpu.audio import learned_vad as LV


def main() -> None:
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 900
    cfg = LV.VadNetConfig()
    real = LV.real_noise_clips()
    print(f"real noise clips: {len(real)}")
    params = LV.train_formant(cfg, steps=steps, seed=0, real_noise=real)
    m = LV.evaluate(cfg, params)
    rn = LV.evaluate_real_negatives(cfg, params, real)
    print("synthetic held-out:", m)
    print("real negatives:", rn)
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "localai_tpu", "assets", "vad-base.safetensors")
    LV.save_params(out, params)
    print("wrote", out)


if __name__ == "__main__":
    main()
